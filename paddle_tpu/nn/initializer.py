"""Weight initializers (ref: python/paddle/fluid/initializer.py).

Every initializer is a pure function of (shape, dtype, PRNG key) — the
TPU-correct analog of the reference's fill ops (``fill_constant``,
``gaussian_random``, ``uniform_random``, ``truncated_gaussian_random``): init
happens on-device in one XLA call, seeded via the global generator
(core/random.py), so multi-host replicas initialize identically.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as prandom
from ..core.dtype import convert_dtype

__all__ = [
    "Initializer", "Constant", "Uniform", "Normal", "TruncatedNormal",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Bilinear", "Assign", "Orthogonal", "Dirac", "calculate_gain",
    "set_global_initializer",
]


def calculate_gain(nonlinearity, param=None):
    recommended = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0, "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    if nonlinearity not in recommended:
        raise ValueError(f"unsupported nonlinearity: {nonlinearity}")
    return recommended[nonlinearity]


def _fans(shape):
    """fan_in/fan_out following the reference's convention: for conv weights
    (OIHW) receptive field multiplies the channel fans."""
    shape = tuple(int(s) for s in shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:  # Linear stores (in, out)
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype="float32", key=None):
        dtype = convert_dtype(dtype)
        if key is None:
            key = prandom.next_key()
        return self._generate(tuple(int(s) for s in shape), dtype, key)

    def _generate(self, shape, dtype, key):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _generate(self, shape, dtype, key):
        return jnp.full(shape, self.value, dtype=dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def _generate(self, shape, dtype, key):
        return jax.random.uniform(key, shape, dtype=jnp.float32,
                                  minval=self.low, maxval=self.high).astype(dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype, key):
        return (jax.random.normal(key, shape, dtype=jnp.float32) * self.std
                + self.mean).astype(dtype)


class TruncatedNormal(Initializer):
    """Samples clipped to ±2σ (ref: truncated_gaussian_random_op)."""

    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype, key):
        z = jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype=jnp.float32)
        return (z * self.std + self.mean).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype, key):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(key, shape, dtype=jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype, key):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(key, shape, dtype=jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, shape, dtype, key):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope) if \
            self.nonlinearity == "leaky_relu" else calculate_gain(self.nonlinearity)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(key, shape, dtype=jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, shape, dtype, key):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope) if \
            self.nonlinearity == "leaky_relu" else calculate_gain(self.nonlinearity)
        std = gain / math.sqrt(fi)
        return (jax.random.normal(key, shape, dtype=jnp.float32) * std).astype(dtype)


class Bilinear(Initializer):
    """Bilinear upsampling kernel for transposed conv (ref: BilinearInitializer)."""

    def _generate(self, shape, dtype, key):
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D weight")
        h, w = shape[2], shape[3]
        f_h, f_w = math.ceil(h / 2.0), math.ceil(w / 2.0)
        c_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        c_w = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        yy = (1 - np.abs(np.arange(h) / f_h - c_h))
        xx = (1 - np.abs(np.arange(w) / f_w - c_w))
        kernel = np.outer(yy, xx).astype(np.float32)
        weight = np.zeros(shape, dtype=np.float32)
        for i in range(shape[0]):
            weight[i, i % shape[1]] = kernel
        return jnp.asarray(weight, dtype=dtype)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def _generate(self, shape, dtype, key):
        v = self.value
        if hasattr(v, "_data"):
            v = v._data
        arr = jnp.asarray(np.asarray(v), dtype=dtype)
        if tuple(arr.shape) != shape:
            arr = arr.reshape(shape)
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def _generate(self, shape, dtype, key):
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = (max(rows, cols), min(rows, cols))
        a = jax.random.normal(key, flat, dtype=jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    """Identity-preserving conv kernel (ref: DiracInitializer)."""

    def __init__(self, groups=1, name=None):
        self.groups = groups

    def _generate(self, shape, dtype, key):
        w = np.zeros(shape, dtype=np.float32)
        out_per_group = shape[0] // self.groups
        centre = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for i in range(min(out_per_group, shape[1])):
                w[(g * out_per_group + i, i) + centre] = 1.0
        return jnp.asarray(w, dtype=dtype)


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    """ref: fluid.set_global_initializer."""
    global _global_weight_init, _global_bias_init
    _global_weight_init, _global_bias_init = weight_init, bias_init


def global_initializer(is_bias):
    return _global_bias_init if is_bias else _global_weight_init


# fluid-era aliases
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
XavierInitializer = XavierUniform
MSRAInitializer = KaimingNormal
BilinearInitializer = Bilinear
NumpyArrayInitializer = Assign

# fluid short names (ref: fluid/initializer.py __all__: Xavier, MSRA)
Xavier = XavierUniform
MSRA = KaimingNormal
__all__ += ["Xavier", "MSRA", "XavierInitializer", "MSRAInitializer"]
