"""Layer: the module base class.

TPU-native analog of ``python/paddle/fluid/dygraph/layers.py`` (class Layer).
A Layer owns Parameters (leaf jax arrays), Buffers (non-trainable state like
BN running stats) and sub-layers, with the reference's state_dict /
named_parameters / hook API. Layers are pure-functional at the jax level:
parameters live outside jit; `paddle_tpu.jit`/`Model` extract the pytree of
params and close the functional train step over it.
"""
from __future__ import annotations

import collections

import numpy as np

from ..core.tensor import Tensor, Parameter
from ..core.dtype import convert_dtype
from ..core import dispatch
from ..utils import unique_name
from . import initializer as I
from .param_attr import ParamAttr

__all__ = ["Layer", "Sequential", "LayerList", "ParameterList", "LayerDict"]


class HookRemoveHelper:
    _next_id = [0]

    def __init__(self, hooks):
        self._hooks = hooks
        self._hook_id = HookRemoveHelper._next_id[0]
        HookRemoveHelper._next_id[0] += 1

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        if name_scope is None:
            name_scope = _camel_to_snake(type(self).__name__)
        self._full_name = unique_name.generate(name_scope)
        self._dtype = convert_dtype(dtype) if dtype is not None else None
        self.training = True
        self._parameters: dict[str, Parameter] = collections.OrderedDict()
        self._buffers: dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names: set[str] = set()
        self._sub_layers: dict[str, Layer] = collections.OrderedDict()
        self._forward_pre_hooks: dict[int, callable] = collections.OrderedDict()
        self._forward_post_hooks: dict[int, callable] = collections.OrderedDict()

    # -- identity -----------------------------------------------------------
    def full_name(self):
        return self._full_name

    # -- train/eval ---------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- parameter creation (ref: LayerObjectHelper / LayerHelperBase) ------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = convert_dtype(dtype) if dtype is not None else (self._dtype or convert_dtype("float32"))
        init = attr.initializer or default_initializer or I.global_initializer(is_bias)
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        name = attr.name or unique_name.generate(self._full_name + ("_b" if is_bias else "_w"))
        data = init(shape, dtype)
        tracer = dispatch.current_tracer()
        if tracer is not None:
            # static mode: create a persistable parameter Variable; the
            # initializer ran eagerly (shapes are known at build time), so
            # the value goes straight into the global scope — the startup
            # program is a no-op (ref: startup initializer ops).
            from ..static_.program import global_scope

            blk = tracer.program.global_block
            v = blk.create_var(name=name, shape=shape, dtype=dtype,
                               persistable=True, stop_gradient=not attr.trainable)
            v.is_parameter = True
            v.trainable = attr.trainable
            v.optimize_attr = {"learning_rate": attr.learning_rate}
            v.regularizer = attr.regularizer
            v.need_clip = attr.need_clip
            global_scope().set(name, data)
            self._parameters[name.replace(".", "_")] = v  # traversal support
            return v
        p = Parameter(data, name=name, trainable=attr.trainable)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_tensor(self, name=None, dtype=None, default_initializer=None):
        dtype = convert_dtype(dtype) if dtype is not None else (self._dtype or convert_dtype("float32"))
        init = default_initializer or I.Constant(0.0)
        t = Tensor(init([], dtype), _internal=True)
        t.name = name or unique_name.generate(self._full_name + "_t")
        return t

    # -- registration -------------------------------------------------------
    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError(f"{name} is not a Parameter")
        self.__dict__.setdefault("_parameters", collections.OrderedDict())
        object.__getattribute__(self, "_parameters")[name] = parameter
        self.__dict__.pop(name, None)
        return parameter

    def add_sublayer(self, name, sublayer):
        if sublayer is not None and not isinstance(sublayer, Layer):
            raise TypeError(f"{name} is not a Layer")
        object.__getattribute__(self, "_sub_layers")[str(name)] = sublayer
        self.__dict__.pop(str(name), None)
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        else:
            self._non_persistable_buffer_names.discard(name)
        self.__dict__.pop(name, None)
        return tensor

    # -- attribute routing --------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            if buffers is not None:
                buffers.pop(name, None)
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            layers[name] = value
            self.__dict__.pop(name, None)
        elif params is not None and name in params:
            if value is None:
                params[name] = None
            elif isinstance(value, Tensor):
                params[name].set_value(value)
            else:
                raise TypeError(f"cannot assign {type(value)} to parameter {name}")
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                buffers[name].set_value(value)
        elif layers is not None and name in layers and value is None:
            layers[name] = None
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._buffers) + list(self._sub_layers)

    # -- traversal ----------------------------------------------------------
    def children(self):
        for _, l in self.named_children():
            yield l

    def named_children(self):
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, l in self.named_children():
            if id(l) in layers_set:
                continue
            sub_prefix = prefix + ("." if prefix else "") + name
            yield from l.named_sublayers(prefix=sub_prefix, include_self=True,
                                         layers_set=layers_set)

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        layers = self.named_sublayers(prefix=prefix, include_self=True) if \
            include_sublayers else [(prefix, self)]
        for lp, layer in layers:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (lp + ("." if lp else "") + name), p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        layers = self.named_sublayers(prefix=prefix, include_self=True) if \
            include_sublayers else [(prefix, self)]
        for lp, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (lp + ("." if lp else "") + name), b

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        helper = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._hook_id] = hook
        return helper

    def register_forward_post_hook(self, hook):
        helper = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._hook_id] = hook
        return helper

    # -- call ---------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError(
            f"{type(self).__name__} must implement forward()")

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        if destination is None:
            destination = collections.OrderedDict()
        prefix = structured_name_prefix
        if prefix and not prefix.endswith("."):
            prefix += "."
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            destination[prefix + name] = p
        # non-persistable buffers are excluded; collect their UNPREFIXED
        # names first so an external prefix can't defeat the lookup
        skip = set()
        for lp, layer in self.named_sublayers(include_self=True):
            for bname in layer._non_persistable_buffer_names:
                skip.add(lp + ("." if lp else "") + bname)
        for name, b in self.named_buffers(include_sublayers=include_sublayers):
            if name in skip:
                continue
            destination[prefix + name] = b
        return destination

    def set_state_dict(self, state_dict, use_structured_name=True):
        missing, unexpected = [], []
        own = self.state_dict()
        for name, value in state_dict.items():
            if name not in own:
                unexpected.append(name)
                continue
            target = own[name]
            v = value._data if isinstance(value, Tensor) else np.asarray(value)
            if tuple(np.shape(v)) != tuple(target.shape):
                raise ValueError(
                    f"shape mismatch for {name}: loaded {np.shape(v)} vs "
                    f"expected {tuple(target.shape)}")
            target.set_value(v)
        for name in own:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- dtype / device -----------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._to_dtype(convert_dtype(dtype))
        return self

    def astype(self, dtype):
        self._to_dtype(convert_dtype(dtype))
        return self

    def _to_dtype(self, dtype):
        import jax.numpy as jnp

        for layer in self.sublayers(include_self=True):
            layer._dtype = dtype
            for p in layer._parameters.values():
                if p is not None and jnp.issubdtype(p.dtype, jnp.floating):
                    p._replace(p._data.astype(dtype))
            for b in layer._buffers.values():
                if b is not None and jnp.issubdtype(b.dtype, jnp.floating):
                    b._replace(b._data.astype(dtype))

    def float(self):
        return self.astype("float32")

    def half(self):
        return self.astype("float16")

    def bfloat16(self):
        return self.astype("bfloat16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self.named_children():
            mod_str = repr(l)
            mod_str = _addindent(mod_str, 2)
            lines.append(f"({name}): {mod_str}")
        main = type(self).__name__ + "("
        if extra:
            main += extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"


def _camel_to_snake(name):
    out = []
    for i, c in enumerate(name):
        if c.isupper() and i > 0:
            out.append("_")
        out.append(c.lower())
    return "".join(out)


def _addindent(s, n):
    lines = s.split("\n")
    if len(lines) == 1:
        return s
    return lines[0] + "\n" + "\n".join(" " * n + l for l in lines[1:])


class Sequential(Layer):
    """ref: dygraph/container.py Sequential."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                len(layers[0]) and isinstance(layers[0][0], (list, tuple)):
            for name, layer in layers[0]:
                self.add_sublayer(name, layer)
        elif len(layers) and isinstance(layers[0], tuple) and len(layers[0]) == 2 \
                and isinstance(layers[0][0], str):
            for name, layer in layers:
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers)
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return self._sub_layers[str(self._abs_idx(idx))]

    def __setitem__(self, idx, layer):
        self.add_sublayer(str(self._abs_idx(idx)), layer)

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def _abs_idx(self, idx):
        return idx + len(self) if idx < 0 else idx

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self.add_sublayer(str(i), l)

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx + len(self) if idx < 0 else idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self)), parameter)
        return self


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        l = self._sub_layers.pop(key)
        return l

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, (dict, LayerDict)) else sublayers
        for key, layer in items:
            self.add_sublayer(key, layer)
        return self
