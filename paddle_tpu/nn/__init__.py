"""paddle_tpu.nn — layers, functional API, initializers.

Mirrors ``paddle.nn`` (ref: python/paddle/nn/__init__.py +
fluid/dygraph/layers.py). TPU-native: layers hold jax-array Parameters;
forward passes are pure traced functions.
"""
from .layer import Layer, Sequential, LayerList, ParameterList, LayerDict  # noqa: F401
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from . import initializer  # noqa: F401
from . import functional  # noqa: F401
from . import nets  # noqa: F401
from .layers import *  # noqa: F401,F403
from .layers import (  # noqa: F401
    common as _common, conv as _conv, pooling as _pooling, norm as _norm,
    activation as _activation, loss as _loss, rnn as _rnn,
    transformer as _transformer,
)

functional_api = functional

# paddle.nn re-exports the gradient clippers (ref: python/paddle/nn
# exposing ClipGradByValue/Norm/GlobalNorm; impl lives in optim/clip.py)
from ..optim.clip import (ClipGradByValue, ClipGradByNorm,  # noqa: F401
                          ClipGradByGlobalNorm)

# the reference's python/paddle/nn/__init__.py binds the functional
# conv ops at nn level too (plain imports; it has no real __all__)
from .functional import (conv2d, conv2d_transpose,  # noqa: F401
                         conv3d, conv3d_transpose)
