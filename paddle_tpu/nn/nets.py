"""Composite network building blocks.

Ref (capability target): python/paddle/fluid/nets.py —
simple_img_conv_pool (:28), img_conv_group (:138), sequence_conv_pool
(:251), glu (:319), scaled_dot_product_attention (:360).

Like the reference, each call CREATES fresh parameters (the fluid
LayerHelper pattern); call once while building a model/program, not per
step. Everything lowers to the same conv/pool/attention ops as the rest
of the framework, so XLA fuses the composites.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from . import functional as F
from .layers.common import Linear, Dropout
from .layers.conv import Conv2D
from .layers.norm import BatchNorm2D

__all__ = ["simple_img_conv_pool", "img_conv_group", "sequence_conv_pool", "multi_box_head",
           "glu", "scaled_dot_product_attention"]


def _act(x, act):
    if act is None:
        return x
    return getattr(F, act)(x)


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1,
                         conv_padding=0, conv_dilation=1, conv_groups=1,
                         param_attr=None, bias_attr=None, act=None,
                         use_cudnn=True):
    """Conv2D + activation + pool2d (ref: nets.py:28)."""
    in_ch = int(input.shape[1])
    conv = Conv2D(in_ch, num_filters, filter_size, stride=conv_stride,
                  padding=conv_padding, dilation=conv_dilation,
                  groups=conv_groups, weight_attr=param_attr,
                  bias_attr=bias_attr)
    out = _act(conv(input), act)
    if global_pooling:
        pool_fn = (F.adaptive_max_pool2d if pool_type == "max"
                   else F.adaptive_avg_pool2d)
        return pool_fn(out, 1)
    pool_fn = F.max_pool2d if pool_type == "max" else F.avg_pool2d
    return pool_fn(out, pool_size, stride=pool_stride,
                   padding=pool_padding)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """VGG-style conv block: N convs (+BN +dropout) then one pool
    (ref: nets.py:138)."""
    if isinstance(conv_num_filter, int):
        conv_num_filter = [conv_num_filter]
    n = len(conv_num_filter)

    def per_conv(v, i):
        return v[i] if isinstance(v, (list, tuple)) else v

    out = input
    for i in range(n):
        in_ch = int(out.shape[1])
        conv = Conv2D(in_ch, conv_num_filter[i],
                      per_conv(conv_filter_size, i),
                      padding=per_conv(conv_padding, i),
                      weight_attr=per_conv(param_attr, i)
                      if param_attr else None)
        out = conv(out)
        if conv_with_batchnorm:
            out = BatchNorm2D(conv_num_filter[i])(out)
            drop = per_conv(conv_batchnorm_drop_rate, i)
            if drop:
                out = Dropout(drop)(out)
        out = _act(out, conv_act)
    pool_fn = F.max_pool2d if pool_type == "max" else F.avg_pool2d
    return pool_fn(out, pool_size, stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None,
                       lengths=None):
    """sequence_conv + activation + sequence_pool over the time axis
    (ref: nets.py:251). input (B, L, D) dense + lengths."""
    D = int(input.shape[-1])
    w = Linear(filter_size * D, num_filters,
               weight_attr=param_attr, bias_attr=bias_attr)
    out = ops.sequence_conv(input, filter_size=filter_size,
                            weight=w.weight, bias=w.bias, lengths=lengths)
    out = _act(out, act)
    return ops.sequence_pool(out, pool_type=pool_type, lengths=lengths)


def glu(input, dim=-1):
    """Gated Linear Unit: split in two along ``dim``, a * sigmoid(b)
    (ref: nets.py:319)."""
    a, b = ops.split(input, 2, axis=dim)
    return a * F.sigmoid(b)


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0, training=True):
    """Multi-head SDPA over (B, L, D) projections-free inputs
    (ref: nets.py:360 — the reference also just reshapes to heads and
    calls the primitive attention)."""
    B, Lq, D = queries.shape[0], queries.shape[1], queries.shape[2]
    Lk = keys.shape[1]
    if D % num_heads:
        raise ValueError(f"hidden {D} not divisible by heads {num_heads}")
    hd = D // num_heads

    def heads_of(t, L):
        t = ops.reshape(t, [B, L, num_heads, hd])
        return ops.transpose(t, [0, 2, 1, 3])

    q, k, v = (heads_of(queries, Lq), heads_of(keys, Lk),
               heads_of(values, Lk))
    att = F.sdpa_bhld(q, k, v, dropout_p=dropout_rate, training=training)
    att = ops.transpose(att, [0, 2, 1, 3])
    return ops.reshape(att, [B, Lq, D])


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head over multiple feature maps (ref:
    fluid/layers/detection.py multi_box_head): per level, a prior_box
    grid plus 3x3/1x1 conv loc + conf predictors; outputs are gathered
    into (B, total_priors, 4) locs, (B, total_priors, C) confs and the
    stacked priors/variances.
    """
    from ..ops.detection import prior_box as _prior_box
    from ..ops.manipulation import concat, reshape, transpose

    n = len(inputs)
    if min_sizes is None:
        # reference ratio schedule: evenly spaced between min/max ratio
        min_sizes, max_sizes = [], []
        step = int((max_ratio - min_ratio) / (n - 2)) if n > 2 else 0
        min_sizes = [base_size * 0.1]
        max_sizes = [base_size * 0.2]
        ratio = min_ratio
        for _ in range(1, n):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
            ratio += step
    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, x in enumerate(inputs):
        ms = min_sizes[i] if isinstance(min_sizes[i], (list, tuple)) \
            else [min_sizes[i]]
        xs = max_sizes[i] if max_sizes is not None else None
        if xs is not None and not isinstance(xs, (list, tuple)):
            xs = [xs]
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i],
                                            (list, tuple)) \
            else [aspect_ratios[i]]
        stp = (0.0, 0.0)
        if steps is not None:
            stp = steps[i] if isinstance(steps[i], (list, tuple)) \
                else (steps[i], steps[i])
        elif step_w is not None or step_h is not None:
            # each is independently optional in the fluid API
            sw = step_w[i] if step_w is not None else step_h[i]
            sh = step_h[i] if step_h is not None else step_w[i]
            stp = (sw, sh)
        b, v = _prior_box(x, image, ms, xs, ar, variance, flip, clip,
                          stp, offset,
                          min_max_aspect_ratios_order=
                          min_max_aspect_ratios_order)
        P = int(b.shape[2])
        boxes_all.append(reshape(b, [-1, 4]))
        vars_all.append(reshape(v, [-1, 4]))
        in_ch = int(x.shape[1])
        loc_conv = Conv2D(in_ch, P * 4, kernel_size, stride=stride,
                          padding=pad)
        conf_conv = Conv2D(in_ch, P * num_classes, kernel_size,
                           stride=stride, padding=pad)
        loc = transpose(loc_conv(x), [0, 2, 3, 1])        # (B, H, W, P*4)
        conf = transpose(conf_conv(x), [0, 2, 3, 1])
        locs.append(reshape(loc, [int(x.shape[0]), -1, 4]))
        confs.append(reshape(conf, [int(x.shape[0]), -1, num_classes]))
    mbox_locs = concat(locs, axis=1)
    mbox_confs = concat(confs, axis=1)
    boxes = concat(boxes_all, axis=0)
    variances = concat(vars_all, axis=0)
    return mbox_locs, mbox_confs, boxes, variances
