"""Build configuration introspection (ref: python/paddle/sysconfig.py:
get_include/get_lib point at the installed headers/libs; here they point
at this package and its native runtime library)."""
import os

__all__ = ["get_include", "get_lib"]

_HERE = os.path.dirname(os.path.abspath(__file__))


def get_include():
    """Directory containing this package's sources (the reference
    returns its C++ header dir; the analog here is the package root —
    the runtime's only native artifact lives beside it)."""
    return os.path.join(_HERE, "runtime", "cc")


def get_lib():
    """Directory containing the native runtime library
    (libptruntime.so, built on first use)."""
    return os.path.join(_HERE, "runtime")
