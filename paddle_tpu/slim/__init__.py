"""Model compression (slim): pruning + distillation.

Capability refs:
- magnitude/structured pruning:
  python/paddle/fluid/contrib/slim/prune/pruner.py:22 (Pruner,
  StructurePruner: pruning_axis + l1_norm criterion, lazy zeroing vs
  real removal), prune_strategy.py (SensitivePruneStrategy,
  UniformPruneStrategy — per-param ratios from a sensitivity scan)
- distillation: slim/distillation/distiller.py:25,108,195 (L2Distiller,
  FSPDistiller, SoftLabelDistiller)
- quantization lives in ``paddle_tpu.quant`` (re-exported here).
- light-NAS (slim/nas/light_nas_strategy.py) is a recorded descope
  (SURVEY §4b): its controller-server search loop is orthogonal
  infrastructure, not a modeling capability.

TPU-first design: pruning is mask-based — weights stay DENSE with zeros
(the layout XLA/MXU execute anyway; there is no sparse speedup to win on
TPU without 2:4-style hardware support), masks are device arrays applied
in one fused multiply, and "real" channel removal is offered as explicit
layer surgery for Sequential-style graphs where shapes may legally
shrink. Distillation losses are plain functions composed into TrainStep
(the frozen teacher rides ``TrainStep(models=[teacher])``).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..nn.layers.common import Linear
from ..nn.layers.conv import Conv2D
from .. import ops

__all__ = [
    "Pruner", "MagnitudePruner", "StructuredPruner",
    "sensitivity", "sensitive_prune_ratios", "uniform_prune",
    "prune_conv_pair",
    "l2_distill", "fsp_matrix", "fsp_distill", "soft_label_distill",
    "DistillConfig", "distill_loss",
]


# ---------------------------------------------------------------------------
# pruning
# ---------------------------------------------------------------------------


def _l1(w, axes):
    return jnp.sum(jnp.abs(w), axis=axes)


def _l2(w, axes):
    return jnp.sqrt(jnp.sum(w * w, axis=axes))


_CRITERIA = {"l1_norm": _l1, "l2_norm": _l2}


class Pruner:
    """Base pruner (ref pruner.py:22): computes a keep-mask per
    parameter; ``prune`` zeroes the dropped entries in place and records
    the mask so ``reapply`` can re-zero after optimizer steps (the
    mask-based analog of the reference's scope surgery)."""

    def __init__(self):
        self.masks: dict = {}

    def _mask_for(self, param, ratio):
        raise NotImplementedError

    def prune(self, model_or_params, ratio=0.5, ratios=None):
        """Zero the lowest-criterion entries. ``ratios`` maps param name
        -> ratio and wins over the uniform ``ratio``."""
        params = model_or_params.parameters() \
            if isinstance(model_or_params, Layer) else list(model_or_params)
        for p in params:
            if p.ndim < 2:  # biases/norm scales are never pruned
                continue
            r = (ratios or {}).get(p.name, ratio)
            if r <= 0.0:
                continue
            mask = self._mask_for(p, float(r))
            self.masks[p.name] = (p, mask)
        self.reapply()
        return self.masks

    def reapply(self):
        """Re-zero pruned entries (call after each optimizer step: dense
        updates regrow pruned weights otherwise)."""
        for p, mask in self.masks.values():
            p._data = p._data * mask.astype(p._data.dtype)

    def sparsity(self):
        """Fraction of zeroed weight entries over all pruned params."""
        tot = zeroed = 0
        for p, mask in self.masks.values():
            tot += mask.size
            zeroed += int(mask.size - jnp.count_nonzero(mask))
        return zeroed / tot if tot else 0.0


class MagnitudePruner(Pruner):
    """Unstructured magnitude pruning: drop the smallest |w| fraction
    per parameter (ref pruner.py Pruner + the lazy path of
    prune_tensor)."""

    def _mask_for(self, param, ratio):
        w = jnp.abs(param._data.astype(jnp.float32)).reshape(-1)
        k = int(np.round(ratio * w.size))
        if k <= 0:
            return jnp.ones(param._data.shape, bool)
        # exactly-k selection via argsort (a magnitude THRESHOLD would
        # drop every tied weight — a constant-filled param at ratio 0.1
        # would be 100% zeroed)
        order = jnp.argsort(w)
        keep = jnp.ones((w.size,), bool).at[order[:k]].set(False)
        return keep.reshape(param._data.shape)


class StructuredPruner(Pruner):
    """Whole-filter (channel) pruning (ref pruner.py:34
    StructurePruner): rank channels along ``pruning_axis`` by the
    criterion over the remaining axes, zero the weakest ``ratio``.
    Default axis 0 — conv filters (out_c, in_c, kh, kw); use axis 1 for
    this framework's (in, out) Linear layout."""

    def __init__(self, pruning_axis=0, criterion="l1_norm"):
        super().__init__()
        self.axis = int(pruning_axis)
        self.criterion = _CRITERIA[criterion]

    def _mask_for(self, param, ratio):
        w = param._data.astype(jnp.float32)
        axes = tuple(i for i in range(w.ndim) if i != self.axis)
        scores = self.criterion(w, axes)
        n = scores.shape[0]
        k = int(np.round(ratio * n))
        if k <= 0:
            return jnp.ones(param._data.shape, bool)
        order = jnp.argsort(scores)
        keep = jnp.ones((n,), bool).at[order[:k]].set(False)
        shape = [1] * w.ndim
        shape[self.axis] = n
        return jnp.broadcast_to(keep.reshape(shape), w.shape)

    def pruned_channels(self, param):
        """Indices of zeroed channels after prune() (for surgery)."""
        _, mask = self.masks[param.name]
        flat = jnp.moveaxis(mask, self.axis, 0).reshape(mask.shape[self.axis],
                                                        -1)
        return np.where(~np.asarray(flat[:, 0]))[0]


def prune_conv_pair(conv, next_layer, ratio, criterion="l1_norm"):
    """REAL channel removal for a conv -> (conv | linear) pair: rebuild
    both layers with the weak output channels of ``conv`` physically
    dropped (ref pruner.py prune_tensor lazy=False). Returns the kept
    channel indices. ``next_layer`` may be None (prune the tail)."""
    w = np.asarray(conv.weight.numpy())  # keep the model's dtype
    wf = w.astype(np.float32)
    scores = np.abs(wf).sum(axis=(1, 2, 3)) if criterion == "l1_norm" \
        else np.sqrt((wf * wf).sum(axis=(1, 2, 3)))
    n = w.shape[0]
    # validate the pair BEFORE mutating anything: a caller catching the
    # error must be left with an untouched, still-runnable model
    if isinstance(next_layer, Linear) and \
            np.asarray(next_layer.weight.numpy()).shape[0] % n != 0:
        raise ValueError(
            f"cannot rewire {type(next_layer).__name__} after "
            f"{type(conv).__name__}: Linear in_features="
            f"{next_layer.weight.shape[0]} is not a multiple of the "
            f"conv's {n} output channels (is there a non-channel-major "
            "flatten or global pooling between them?)")
    if next_layer is not None and \
            not isinstance(next_layer, (Conv2D, Linear)):
        raise TypeError(f"cannot rewire {type(next_layer).__name__} "
                        "after channel removal")
    k = int(np.round(ratio * n))
    keep = np.sort(np.argsort(scores)[k:])
    conv.weight._data = jnp.asarray(w[keep])
    if conv.bias is not None:
        conv.bias._data = jnp.asarray(
            np.asarray(conv.bias.numpy())[keep])
    conv._out_channels = len(keep)
    if isinstance(next_layer, Conv2D):
        nw = np.asarray(next_layer.weight.numpy())
        next_layer.weight._data = jnp.asarray(nw[:, keep])
        next_layer._in_channels = len(keep)
    elif isinstance(next_layer, Linear):
        # (in, out) rows grouped per input channel (e.g. after flatten):
        # keep the row blocks belonging to surviving channels
        nw = np.asarray(next_layer.weight.numpy())
        per = nw.shape[0] // n  # divisibility validated up front
        rows = np.concatenate([np.arange(c * per, (c + 1) * per)
                               for c in keep])
        next_layer.weight._data = jnp.asarray(nw[rows])
    return keep


def sensitivity(model, eval_fn, params=None, ratios=(0.1, 0.3, 0.5, 0.7),
                pruner=None):
    """Per-parameter sensitivity scan (ref prune_strategy.py
    SensitivePruneStrategy._compute_sensitivities): prune ONE parameter
    at a time at each ratio, measure ``eval_fn()`` (higher = better),
    restore, and return {param_name: {ratio: metric_loss}} where
    metric_loss = baseline - pruned metric."""
    pruner = pruner or StructuredPruner()
    params = [p for p in (params or model.parameters()) if p.ndim >= 2]
    base = float(eval_fn())
    out = {}
    for p in params:
        saved = p._data
        out[p.name] = {}
        for r in ratios:
            mask = pruner._mask_for(p, float(r))
            p._data = saved * mask.astype(saved.dtype)
            out[p.name][float(r)] = base - float(eval_fn())
            p._data = saved
    return out


def sensitive_prune_ratios(sens, target_loss=0.05):
    """Turn a sensitivity table into per-param ratios: the largest
    scanned ratio whose metric loss stays within ``target_loss``
    (greedy rule of SensitivePruneStrategy)."""
    ratios = {}
    for name, table in sens.items():
        best = 0.0
        for r in sorted(table):
            if table[r] <= target_loss:
                best = r
        if best > 0.0:
            ratios[name] = best
    return ratios


def uniform_prune(model, ratio, pruner=None):
    """UniformPruneStrategy: one ratio for every prunable param."""
    pruner = pruner or StructuredPruner()
    pruner.prune(model, ratio=ratio)
    return pruner


# ---------------------------------------------------------------------------
# distillation
# ---------------------------------------------------------------------------


def l2_distill(teacher_feat, student_feat):
    """Mean squared feature distance (ref distiller.py:25 L2Distiller)."""
    d = teacher_feat - student_feat
    return ops.mean(d * d)


def fsp_matrix(feat_a, feat_b):
    """Flow-of-solution-procedure matrix (ref distiller.py:191
    _fsp_matrix): (N, C1, H, W) x (N, C2, H, W) -> (N, C1, C2),
    normalized by H*W. Built from taped ops so gradients flow to the
    student features."""
    n, c1, h, w = feat_a.shape
    c2 = feat_b.shape[1]
    am = ops.reshape(feat_a.astype("float32"), [n, c1, h * w])
    bm = ops.reshape(feat_b.astype("float32"), [n, c2, h * w])
    return ops.matmul(am, ops.transpose(bm, [0, 2, 1])) * (1.0 / (h * w))


def fsp_distill(teacher_pairs, student_pairs):
    """Mean L2 between teacher and student FSP matrices over
    corresponding (begin, end) feature pairs (ref distiller.py:108
    FSPDistiller)."""
    losses = []
    for (ta, tb), (sa, sb) in zip(teacher_pairs, student_pairs):
        tm = fsp_matrix(ta, tb)
        sm = fsp_matrix(sa, sb)
        d = tm - sm
        losses.append(ops.mean(d * d))
    total = losses[0]
    for l in losses[1:]:
        total = total + l
    return total / float(len(losses))


def soft_label_distill(teacher_logits, student_logits,
                       teacher_temperature=2.0, student_temperature=2.0):
    """Soft-target cross entropy (ref distiller.py:195
    SoftLabelDistiller): CE(softmax(t/Tt), log_softmax(s/Ts)). Taped ops
    throughout — the student side must receive gradients."""
    p_t = ops.softmax(
        teacher_logits.astype("float32") * (1.0 / teacher_temperature),
        axis=-1)
    log_s = ops.log_softmax(
        student_logits.astype("float32") * (1.0 / student_temperature),
        axis=-1)
    return ops.mean(ops.sum(p_t * log_s, axis=-1)) * -1.0


class DistillConfig:
    """Weights for the combined distillation objective."""

    def __init__(self, task_weight=1.0, soft_label_weight=1.0,
                 l2_weight=0.0, fsp_weight=0.0, temperature=2.0):
        self.task_weight = task_weight
        self.soft_label_weight = soft_label_weight
        self.l2_weight = l2_weight
        self.fsp_weight = fsp_weight
        self.temperature = temperature


def distill_loss(task_loss, teacher_logits, student_logits,
                 config=None, teacher_feats=None, student_feats=None):
    """Compose the standard distillation objective. Use inside a
    TrainStep loss_fn with the frozen teacher passed via
    ``TrainStep(models=[teacher])`` so its (non-trainable) params ride
    the compiled step."""
    cfg = config or DistillConfig()
    loss = task_loss * cfg.task_weight
    if cfg.soft_label_weight:
        loss = loss + soft_label_distill(
            teacher_logits, student_logits,
            cfg.temperature, cfg.temperature) * cfg.soft_label_weight
    if (cfg.l2_weight or cfg.fsp_weight) and (teacher_feats or
                                              student_feats):
        if not (teacher_feats and student_feats) or \
                len(teacher_feats) != len(student_feats):
            raise ValueError(
                "feature distillation needs teacher_feats and "
                "student_feats of equal length")
    if cfg.l2_weight and teacher_feats:
        for tf, sf in zip(teacher_feats, student_feats):
            loss = loss + l2_distill(tf, sf) * cfg.l2_weight
    if cfg.fsp_weight and teacher_feats and len(teacher_feats) >= 2:
        pairs_t = list(zip(teacher_feats[:-1], teacher_feats[1:]))
        pairs_s = list(zip(student_feats[:-1], student_feats[1:]))
        loss = loss + fsp_distill(pairs_t, pairs_s) * cfg.fsp_weight
    return loss


# quantization is the fourth slim pillar — implemented in paddle_tpu.quant
from .. import quant  # noqa: E402,F401
from ..quant import (quantize_model, PostTrainingQuantization,  # noqa: E402,F401
                     fake_quantize_abs_max)

# 1.x class surface: the Compressor framework (ref: contrib/slim/core)
from .compressor import (  # noqa: E402,F401
    Compressor, Context, Strategy, ConfigFactory,
    PruneStrategy, UniformPruneStrategy, SensitivePruneStrategy,
    AutoPruneStrategy, StructurePruner,
    DistillationStrategy, L2Distiller, FSPDistiller, SoftLabelDistiller,
    QuantizationStrategy, MKLDNNPostTrainingQuantStrategy,
    LightNASStrategy, SearchSpace, ControllerServer, SearchAgent,
    EvolutionaryController, SAController,
    GraphWrapper, VarWrapper, OpWrapper, SlimGraphExecutor,
)
from ..quant.passes import (  # noqa: E402,F401
    QuantizationTransformPass, QuantizationFreezePass, ConvertToInt8Pass,
    TransformForMobilePass, OutScaleForTrainingPass,
    OutScaleForInferencePass, AddQuantDequantPass, QuantizeTranspiler,
)

__all__ += [
    "Compressor", "Context", "Strategy", "ConfigFactory",
    "PruneStrategy", "UniformPruneStrategy", "SensitivePruneStrategy",
    "AutoPruneStrategy", "StructurePruner", "DistillationStrategy",
    "L2Distiller", "FSPDistiller", "SoftLabelDistiller",
    "QuantizationStrategy", "EvolutionaryController", "SAController",
    "GraphWrapper", "VarWrapper", "OpWrapper", "SlimGraphExecutor",
    "QuantizationTransformPass", "QuantizationFreezePass",
    "ConvertToInt8Pass", "TransformForMobilePass",
    "OutScaleForTrainingPass", "OutScaleForInferencePass",
    "AddQuantDequantPass", "QuantizeTranspiler",
]
