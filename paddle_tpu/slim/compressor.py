"""slim 1.x class surface: the Compressor framework
(ref: python/paddle/fluid/contrib/slim/{core,prune,distillation,
quantization,graph,searcher}/).

The reference Compressor rewrites ProgramDesc graphs (channel surgery,
distiller sub-graphs, quant op insertion) driven by epoch-scheduled
Strategies from a yaml config. The XLA-era redesign keeps the 1.x
class names, the yaml schema, and the Strategy callback protocol
(on_compression_begin/epoch/batch/...), but composes over eager
``nn.Layer`` models instead of program surgery:

- pruning = persistent magnitude masks re-applied after each update
  (dense masked arrays — the TPU-friendly form; see slim/__init__.py);
- distillation = forward hooks capturing named teacher/student
  features, combined into the loss via slim's distill primitives;
- quantization = QAT wrapping (quant/) on a schedule.

GraphWrapper remains graph-level: it wraps a static ``Program`` for
inspection, as the reference wraps IrGraph.
"""
from __future__ import annotations

import logging
import math
import os
import re

import numpy as np

from ..fluid.log_helper import get_logger

_logger = get_logger(__name__, logging.INFO,
                     fmt="%(asctime)s-%(levelname)s: %(message)s")

__all__ = [
    "Context", "Strategy", "Compressor", "ConfigFactory",
    "PruneStrategy", "UniformPruneStrategy", "SensitivePruneStrategy",
    "AutoPruneStrategy", "StructurePruner",
    "DistillationStrategy", "L2Distiller", "FSPDistiller",
    "SoftLabelDistiller", "QuantizationStrategy",
    "MKLDNNPostTrainingQuantStrategy", "QatInt8MkldnnPass",
    "Qat2Int8MkldnnPass", "LightNASStrategy", "SearchSpace",
    "ControllerServer", "SearchAgent", "EvolutionaryController",
    "SAController", "GraphWrapper", "VarWrapper", "OpWrapper",
    "SlimGraphExecutor",
]


class Context:
    """ref: core/compressor.py:77 — the state bag strategies see."""

    def __init__(self, place=None, scope=None, train_graph=None,
                 eval_graph=None, optimizer=None, eval_func=None):
        self.place = place
        self.scope = scope
        self.train_graph = train_graph      # the model (nn.Layer)
        self.eval_graph = eval_graph or train_graph
        self.optimizer = optimizer
        self.eval_func = eval_func
        self.epoch_id = 0
        self.batch_id = 0
        self.batch = None                   # current (inputs...) tuple
        self.k_v = {}
        self.eval_results = {}

    def run_eval_graph(self, sampled_rate=None, cached_id=0):
        """ref: compressor.py:171 — evaluate and record the result."""
        if self.eval_func is None:
            raise ValueError("no eval_func configured")
        res = float(self.eval_func(self.eval_graph))
        self.eval_results.setdefault("metric", []).append(res)
        return res, None

    def put(self, key, value):
        self.k_v[key] = value

    def get(self, key):
        return self.k_v.get(key)


class Strategy:
    """ref: core/strategy.py:18 — epoch-scheduled callback bundle."""

    def __init__(self, start_epoch=0, end_epoch=0):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch

    def __getstate__(self):
        d = {}
        for k, v in self.__dict__.items():
            if not isinstance(v, (int, float, str, list, dict, tuple,
                                  type(None))):
                continue
            d[k] = v
        return d

    def on_compression_begin(self, context):
        pass

    def on_epoch_begin(self, context):
        pass

    def on_epoch_end(self, context):
        pass

    def on_batch_begin(self, context):
        pass

    def on_batch_end(self, context):
        pass

    def on_compression_end(self, context):
        pass

    def restore_from_checkpoint(self, context):
        pass

    def loss_terms(self, context):
        """Extra loss tensors the Compressor adds while this strategy is
        active (XLA-era hook; distillation uses it)."""
        return []


# -- pruning ----------------------------------------------------------------

from . import MagnitudePruner, StructuredPruner  # noqa: E402

# ref: prune/pruner.py StructurePruner — axis/criterion channel pruner;
# the structured (whole-filter) pruner is the same object here
StructurePruner = StructuredPruner


class PruneStrategy(Strategy):
    """ref: prune/prune_strategy.py:36 — magnitude masks over params
    matching ``pruned_params`` (a regex on parameter names), re-applied
    after every optimizer step so pruned weights stay dead."""

    def __init__(self, pruner=None, start_epoch=0, end_epoch=0,
                 target_ratio=0.5, metric_name=None,
                 pruned_params="conv.*_w.*|.*weight.*"):
        super().__init__(start_epoch, end_epoch)
        self.pruner = pruner or MagnitudePruner()
        self.target_ratio = target_ratio
        self.metric_name = metric_name
        self.pruned_params = pruned_params

    def _target_params(self, model):
        """Params whose hierarchical name OR unique param name matches
        the regex (the reference matches on param names)."""
        pat = re.compile(self.pruned_params)
        out = []
        for name, p in model.named_parameters():
            if (pat.search(name) or pat.search(p.name)) and \
                    len(p.shape) >= 2:  # biases/scalars never pruned
                out.append((name, p))
        return out

    def _ratios(self, context):
        return {name: self.target_ratio
                for name, _ in self._target_params(context.train_graph)}

    def _build_masks(self, context):
        by_name = self._ratios(context)
        targets = self._target_params(context.train_graph)
        # Pruner keys ratios on the unique param name
        self.pruner.prune([p for _, p in targets],
                          ratios={p.name: by_name[n]
                                  for n, p in targets})

    def on_epoch_begin(self, context):
        if context.epoch_id == self.start_epoch:
            self._build_masks(context)
            _logger.info(f"pruned {self.sparsity():.1%} of targeted "
                         "weights")

    def on_batch_end(self, context):
        if self.pruner.masks and context.epoch_id >= self.start_epoch:
            self.pruner.reapply()

    def sparsity(self):
        return self.pruner.sparsity()


class UniformPruneStrategy(PruneStrategy):
    """ref: prune_strategy.py:563 — one ratio for every target param."""


class SensitivePruneStrategy(PruneStrategy):
    """ref: prune_strategy.py:672 — per-param ratios from a sensitivity
    scan (slim.sensitivity): prune less where the metric degrades
    fastest."""

    def __init__(self, pruner=None, start_epoch=0, end_epoch=0,
                 target_ratio=0.5, metric_name=None,
                 pruned_params=".*weight.*", eval_rate=None,
                 sensitivities_file=None, sensitivities=None,
                 num_steps=1, delta_rate=0.2):
        super().__init__(pruner, start_epoch, end_epoch, target_ratio,
                         metric_name, pruned_params)
        self.sensitivities = sensitivities or {}

    def _ratios(self, context):
        from . import sensitive_prune_ratios, sensitivity

        model = context.train_graph
        targets = self._target_params(model)
        if not self.sensitivities:
            if context.eval_func is None:
                raise ValueError(
                    "SensitivePruneStrategy needs eval_func (or a "
                    "precomputed sensitivities= dict)")
            # sensitivity() wants Parameter objects and a zero-arg
            # eval_fn (higher = better)
            self.sensitivities = sensitivity(
                model, lambda: float(context.eval_func(model)),
                params=[p for _, p in targets])
        # sensitivities key on unique param names; map back to the
        # hierarchical names _build_masks ratios use
        by_pname = sensitive_prune_ratios(self.sensitivities,
                                          target_loss=self.target_ratio)
        mean = (sum(by_pname.values()) / len(by_pname)) if by_pname \
            else self.target_ratio
        # accept either key spelling (unique param name or hierarchical)
        return {n: by_pname.get(p.name, by_pname.get(n, mean))
                for n, p in targets}


class AutoPruneStrategy(PruneStrategy):
    """ref: prune/auto_prune_strategy.py — controller-searched per-param
    ratios; each on_epoch_begin proposes tokens via SAController, prunes
    accordingly, and rewards the controller with the eval metric."""

    def __init__(self, pruner=None, start_epoch=0, end_epoch=10,
                 target_ratio=0.5, metric_name=None,
                 pruned_params=".*weight.*", retrain_epoch=0,
                 controller=None):
        super().__init__(pruner, start_epoch, end_epoch, target_ratio,
                         metric_name, pruned_params)
        self._controller = controller
        self._levels = [max(0.0, target_ratio - 0.2), target_ratio,
                        min(0.95, target_ratio + 0.2)]
        self._tokens = None

    def on_epoch_begin(self, context):
        if not (self.start_epoch <= context.epoch_id <= self.end_epoch):
            return
        names = [n for n, _ in self._target_params(context.train_graph)]
        if self._controller is None:
            self._controller = SAController(
                range_table=[len(self._levels)] * len(names))
        self._tokens = self._controller.next_tokens()
        self._ratio_map = {n: self._levels[t]
                           for n, t in zip(names, self._tokens)}
        self._build_masks(context)

    def _ratios(self, context):
        return getattr(self, "_ratio_map", None) or super()._ratios(context)

    def on_epoch_end(self, context):
        if self._tokens is not None and context.eval_func is not None:
            reward, _ = context.run_eval_graph()
            self._controller.update(self._tokens, reward)


# -- distillation ------------------------------------------------------------


class _FeatureTap:
    """Forward hooks capturing named sublayer outputs."""

    def __init__(self, model, names):
        self.feats = {}
        self._handles = []
        wanted = set(names)
        for name, layer in model.named_sublayers():
            if name in wanted:
                self._handles.append(layer.register_forward_post_hook(
                    self._make(name)))
                wanted.discard(name)
        if wanted:
            raise ValueError(f"sublayers not found for distillation: "
                             f"{sorted(wanted)}")

    def _make(self, name):
        def hook(layer, inputs, output):
            self.feats[name] = output
            return output

        return hook

    def remove(self):
        for h in self._handles:
            h.remove()


class L2Distiller:
    """ref: distillation/distiller.py:25 — L2 between a student and a
    teacher feature map (sublayer names)."""

    def __init__(self, student_feature_map, teacher_feature_map,
                 distillation_loss_weight=1.0):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.weight = distillation_loss_weight

    def distiller_loss(self, s_feats, t_feats):
        from . import l2_distill

        return self.weight * l2_distill(
            t_feats[self.teacher_feature_map],
            s_feats[self.student_feature_map])

    def student_names(self):
        return [self.student_feature_map]

    def teacher_names(self):
        return [self.teacher_feature_map]


class FSPDistiller:
    """ref: distiller.py:103 — match flow-of-solution-procedure matrices
    between (start, end) feature pairs."""

    def __init__(self, student_pairs, teacher_pairs,
                 distillation_loss_weight=1.0):
        self.student_pairs = student_pairs
        self.teacher_pairs = teacher_pairs
        self.weight = distillation_loss_weight

    def distiller_loss(self, s_feats, t_feats):
        from . import fsp_distill

        t = [(t_feats[a], t_feats[b]) for a, b in self.teacher_pairs]
        s = [(s_feats[a], s_feats[b]) for a, b in self.student_pairs]
        return self.weight * fsp_distill(t, s)

    def student_names(self):
        return [n for pair in self.student_pairs for n in pair]

    def teacher_names(self):
        return [n for pair in self.teacher_pairs for n in pair]


class SoftLabelDistiller:
    """ref: distiller.py:195 — KL between temperature-softened
    teacher/student logits."""

    def __init__(self, student_feature_map=None, teacher_feature_map=None,
                 student_temperature=1.0, teacher_temperature=1.0,
                 distillation_loss_weight=1.0):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.student_temperature = student_temperature
        self.teacher_temperature = teacher_temperature
        self.weight = distillation_loss_weight

    def distiller_loss(self, s_feats, t_feats):
        from . import soft_label_distill

        return self.weight * soft_label_distill(
            t_feats[self.teacher_feature_map],
            s_feats[self.student_feature_map],
            teacher_temperature=self.teacher_temperature,
            student_temperature=self.student_temperature)

    def student_names(self):
        return [self.student_feature_map]

    def teacher_names(self):
        return [self.teacher_feature_map]


class DistillationStrategy(Strategy):
    """ref: distillation/distillation_strategy.py — while active, the
    Compressor adds each distiller's loss (teacher features captured by
    hooks on the teacher model running the same batch)."""

    def __init__(self, distillers=None, start_epoch=0, end_epoch=0,
                 teacher=None):
        super().__init__(start_epoch, end_epoch)
        self.distillers = distillers or []
        self.teacher = teacher
        self._s_tap = self._t_tap = None

    def on_compression_begin(self, context):
        if self.teacher is None:
            self.teacher = context.get("teacher")
        if self.teacher is None:
            raise ValueError("DistillationStrategy needs a teacher model "
                             "(pass teacher= or context.put('teacher', m))")
        s_names = [n for d in self.distillers for n in d.student_names()]
        t_names = [n for d in self.distillers for n in d.teacher_names()]
        self._s_tap = _FeatureTap(context.train_graph, s_names)
        self._t_tap = _FeatureTap(self.teacher, t_names)
        self.teacher.eval()

    def loss_terms(self, context):
        if not (self.start_epoch <= context.epoch_id <= self.end_epoch):
            return []
        # teacher forward on the SAME model inputs the student saw:
        # batch convention is (inputs..., label), so everything but the
        # trailing label feeds the teacher (no grad)
        from ..core import no_grad

        args = context.batch[:-1] if len(context.batch) > 1 \
            else context.batch
        with no_grad():
            self.teacher(*args)
        return [d.distiller_loss(self._s_tap.feats, self._t_tap.feats)
                for d in self.distillers]

    def on_compression_end(self, context):
        if self._s_tap:
            self._s_tap.remove()
        if self._t_tap:
            self._t_tap.remove()


# -- quantization ------------------------------------------------------------


class QuantizationStrategy(Strategy):
    """ref: quantization/quantization_strategy.py — QAT-wrap the model
    at start_epoch (fake-quant STE from quant/); after end_epoch the
    trained scales ship via quantize_inference_model."""

    def __init__(self, start_epoch=0, end_epoch=0, weight_bits=8,
                 activation_bits=8, float_model_save_path=None,
                 int8_model_save_path=None, **kw):
        super().__init__(start_epoch, end_epoch)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.float_model_save_path = float_model_save_path
        self.int8_model_save_path = int8_model_save_path
        self._qat = None

    def on_epoch_begin(self, context):
        if context.epoch_id == self.start_epoch and self._qat is None:
            from ..quant import QAT

            self._qat = QAT(bits=self.weight_bits,
                            quantize_inputs=self.activation_bits > 0)
            context.train_graph = self._qat.quantize(context.train_graph)
            context.eval_graph = context.train_graph
            _logger.info("QAT wrapping applied "
                         f"(w{self.weight_bits}/a{self.activation_bits})")

    def on_compression_end(self, context):
        """ref behavior: emit the float and the converted int8 model at
        the end of the schedule."""
        from ..framework.io import save

        if self.float_model_save_path:
            os.makedirs(self.float_model_save_path, exist_ok=True)
            save(context.train_graph.state_dict(),
                 os.path.join(self.float_model_save_path,
                              "model.pdparams"))
        if self.int8_model_save_path and self._qat is not None:
            context.train_graph = self._qat.convert(context.train_graph)
            context.eval_graph = context.train_graph
            os.makedirs(self.int8_model_save_path, exist_ok=True)
            save(context.train_graph.state_dict(),
                 os.path.join(self.int8_model_save_path,
                              "model.pdparams"))


_MKLDNN_DESCOPE = (
    "MKLDNN int8 lowering is Intel-x86 specific (SURVEY §4b descope); "
    "on TPU the int8 path is quant.quantize_inference_model -> "
    "Predictor (XLA lowering)")


class MKLDNNPostTrainingQuantStrategy(Strategy):
    """ref: quantization/mkldnn_post_training_strategy.py — x86-only
    graph lowering; recorded descope."""

    def __init__(self, *a, **k):
        raise NotImplementedError(_MKLDNN_DESCOPE)


class QatInt8MkldnnPass:
    """ref: qat_int8_mkldnn_pass.py — recorded descope."""

    def __init__(self, *a, **k):
        raise NotImplementedError(_MKLDNN_DESCOPE)


class Qat2Int8MkldnnPass(QatInt8MkldnnPass):
    """ref: qat2_int8_mkldnn_pass.py — recorded descope."""


_NAS_DESCOPE = (
    "slim light-NAS is a controller-server search harness (SURVEY §4b "
    "descope); the searchable capabilities (pruning ratios, quant, "
    "distillation) are all live in paddle_tpu.slim — drive them with "
    "SAController in plain user code")


class LightNASStrategy(Strategy):
    def __init__(self, *a, **k):
        raise NotImplementedError(_NAS_DESCOPE)


class SearchSpace:
    """ref: nas/search_space.py — abstract token space. Subclass and
    implement init_tokens/range_table/create_net (the controller side,
    SAController, is live)."""

    def init_tokens(self):
        raise NotImplementedError

    def range_table(self):
        raise NotImplementedError

    def create_net(self, tokens=None):
        raise NotImplementedError


class ControllerServer:
    def __init__(self, *a, **k):
        raise NotImplementedError(_NAS_DESCOPE)


class SearchAgent:
    def __init__(self, *a, **k):
        raise NotImplementedError(_NAS_DESCOPE)


# -- searcher ----------------------------------------------------------------


class EvolutionaryController:
    """ref: searcher/controller.py — propose/update protocol."""

    def update(self, tokens, reward):
        raise NotImplementedError

    def reset(self, range_table, constrain_func=None):
        raise NotImplementedError

    def next_tokens(self):
        raise NotImplementedError


class SAController(EvolutionaryController):
    """ref: searcher/controller.py SAController — simulated annealing
    over integer token vectors."""

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024, max_try_times=None, seed=0):
        self._range_table = list(range_table or [])
        self._reduce_rate = reduce_rate
        self._init_temperature = init_temperature
        self._max_try_times = max_try_times
        self._rng = np.random.RandomState(seed)
        self._iter = 0
        self._tokens = [self._rng.randint(0, r)
                        for r in self._range_table]
        self._reward = -math.inf
        self._best_tokens = list(self._tokens)
        self._best_reward = -math.inf
        self._constrain_func = None

    def reset(self, range_table, constrain_func=None):
        self._range_table = list(range_table)
        self._constrain_func = constrain_func
        self._tokens = [self._rng.randint(0, r)
                        for r in self._range_table]

    def next_tokens(self):
        """Mutate one position of the current tokens."""
        new = list(self._tokens)
        if new:
            for _ in range(100):
                i = self._rng.randint(0, len(new))
                new[i] = self._rng.randint(0, self._range_table[i])
                if self._constrain_func is None or \
                        self._constrain_func(new):
                    break
        return new

    def update(self, tokens, reward):
        """Metropolis accept/reject at the current temperature."""
        self._iter += 1
        temp = self._init_temperature * (self._reduce_rate ** self._iter)
        if reward > self._reward or self._rng.rand() <= math.exp(
                min(0.0, (reward - self._reward)) / max(temp, 1e-9)):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._best_reward:
            self._best_reward = reward
            self._best_tokens = list(tokens)

    @property
    def best_tokens(self):
        return list(self._best_tokens)

    @property
    def max_reward(self):
        return self._best_reward


# -- graph wrappers ----------------------------------------------------------


class VarWrapper:
    """ref: graph/graph_wrapper.py VarWrapper over a Program var."""

    def __init__(self, var, graph):
        self._var = var
        self._graph = graph

    def name(self):
        return self._var.name

    def shape(self):
        return list(self._var.shape)

    def is_parameter(self):
        return bool(getattr(self._var, "is_parameter", False))

    def is_persistable(self):
        return bool(getattr(self._var, "persistable", False))

    def inputs(self):
        return [OpWrapper(op, self._graph)
                for op in self._graph._program.global_block.ops
                if self._var.name in op.output_names]

    def outputs(self):
        return [OpWrapper(op, self._graph)
                for op in self._graph._program.global_block.ops
                if self._var.name in op.input_names]


class OpWrapper:
    """ref: graph_wrapper.py OpWrapper over a Program op."""

    def __init__(self, op, graph):
        self._op = op
        self._graph = graph

    def type(self):
        return self._op.type

    def attr(self, name):
        return self._op.attrs.get(name)

    def all_inputs(self):
        blk = self._graph._program.global_block
        return [VarWrapper(blk.var(n), self._graph)
                for n in self._op.input_names
                if n is not None and blk.has_var(n)]

    def all_outputs(self):
        blk = self._graph._program.global_block
        return [VarWrapper(blk.var(n), self._graph)
                for n in self._op.output_names if blk.has_var(n)]


class GraphWrapper:
    """ref: graph_wrapper.py:33 — inspection over a static Program."""

    def __init__(self, program, in_nodes=None, out_nodes=None):
        self._program = program
        self.in_nodes = dict(in_nodes or {})
        self.out_nodes = dict(out_nodes or {})

    def all_parameters(self):
        return [VarWrapper(v, self)
                for v in self._program.global_block.all_parameters()]

    def vars(self):
        return [VarWrapper(v, self)
                for v in self._program.global_block.vars.values()]

    def var(self, name):
        return VarWrapper(self._program.global_block.var(name), self)

    def ops(self):
        return [OpWrapper(op, self)
                for op in self._program.global_block.ops]

    def numel_params(self):
        return int(sum(np.prod(v.shape()) or 0
                       for v in self.all_parameters()))

    def program(self):
        return self._program


class SlimGraphExecutor:
    """ref: graph/executor.py — thin Executor front over a wrapped
    graph."""

    def __init__(self, place=None):
        from ..static_ import Executor

        self._exe = Executor(place)

    def run(self, graph, scope=None, data=None, feed=None,
            fetch_list=None):
        program = graph.program() if isinstance(graph, GraphWrapper) \
            else graph
        fetches = fetch_list or list(
            getattr(graph, "out_nodes", {}).values())
        return self._exe.run(program, feed=feed or data,
                             fetch_list=fetches, scope=scope)


# -- config + compressor ------------------------------------------------------


_STRATEGY_CLASSES = {}


def _register_strategies():
    for c in (UniformPruneStrategy, SensitivePruneStrategy,
              AutoPruneStrategy, PruneStrategy, DistillationStrategy,
              QuantizationStrategy, MKLDNNPostTrainingQuantStrategy,
              LightNASStrategy):
        _STRATEGY_CLASSES[c.__name__] = c


_register_strategies()


class ConfigFactory:
    """ref: core/config.py — parse the 1.x slim yaml schema::

        version: 1.0
        strategies:
          prune_s:
            class: UniformPruneStrategy
            target_ratio: 0.5
        compressor:
          epoch: 3
          strategies: [prune_s]

    Accepts a yaml path or an equivalent dict."""

    def __init__(self, config):
        if isinstance(config, str):
            import yaml

            with open(config) as f:
                config = yaml.safe_load(f)
        self._cfg = config or {}
        self.compressor = dict(self._cfg.get("compressor", {}))
        # auxiliary sections build first so strategies can reference
        # their entries BY NAME (the 1.x schema: pruner: 'pruner_1')
        self._named = {}
        aux_classes = {
            "pruners": {"StructurePruner": StructurePruner,
                        "MagnitudePruner": MagnitudePruner,
                        "Pruner": MagnitudePruner},
            "distillers": {"L2Distiller": L2Distiller,
                           "FSPDistiller": FSPDistiller,
                           "SoftLabelDistiller": SoftLabelDistiller},
            "controllers": {"SAController": SAController,
                            "EvolutionaryController":
                                EvolutionaryController},
        }
        for section, classes in aux_classes.items():
            for name, spec in (self._cfg.get(section) or {}).items():
                spec = dict(spec)
                cls = classes[spec.pop("class")]
                try:
                    self._named[name] = cls(**spec)
                except TypeError:
                    # pruner classes take criterion-style kwargs the
                    # reference schema sometimes omits/renames; fall
                    # back to a default instance
                    self._named[name] = cls()
        self._instances = {}
        for name, spec in (self._cfg.get("strategies") or {}).items():
            spec = {k: self._resolve(v) for k, v in dict(spec).items()}
            cls_name = spec.pop("class")
            cls = _STRATEGY_CLASSES[cls_name]
            self._instances[name] = cls(**spec)

    def _resolve(self, value):
        """A string (or list of strings) naming an aux-section entry
        resolves to the built instance."""
        if isinstance(value, str) and value in self._named:
            return self._named[value]
        if isinstance(value, list):
            return [self._named.get(v, v) if isinstance(v, str) else v
                    for v in value]
        return value

    def instance(self, name):
        return self._instances[name]

    def strategies(self):
        names = self.compressor.get("strategies") or \
            list(self._instances)
        return [self._instances[n] for n in names]


class Compressor:
    """ref: core/compressor.py:238 — the epoch loop driving strategies.

    XLA-era signature: the model is an eager ``nn.Layer`` (``model=``,
    or positionally where the reference takes ``train_program``); the
    reader yields ``(inputs..., label)`` numpy batches; ``loss_fn(model,
    *batch) -> scalar Tensor`` replaces the fetch-list loss var; the
    optimizer is a live paddle_tpu optimizer. eval_func(model) -> float.
    """

    def __init__(self, place=None, scope=None, train_program=None,
                 train_reader=None, train_feed_list=None,
                 train_fetch_list=None, eval_program=None,
                 eval_reader=None, eval_feed_list=None,
                 eval_fetch_list=None, eval_func=None,
                 save_eval_model=True, prune_infer_model=None,
                 teacher_programs=(), checkpoint_path=None,
                 train_optimizer=None, distiller_optimizer=None,
                 search_space=None, log_period=20, model=None,
                 loss_fn=None, epoch=1):
        self.model = model if model is not None else train_program
        if self.model is None:
            raise ValueError("pass the model (nn.Layer) as model= or "
                             "train_program=")
        self.train_reader = train_reader
        self.eval_func = eval_func
        self.optimizer = train_optimizer
        self.loss_fn = loss_fn
        self.checkpoint_path = checkpoint_path
        self.log_period = log_period
        self.epoch = epoch
        self.strategies = []
        self.teachers = list(teacher_programs)
        self.place = place
        self.scope = scope

    def config(self, config):
        """Load strategies from a yaml path / dict / ConfigFactory."""
        factory = config if isinstance(config, ConfigFactory) \
            else ConfigFactory(config)
        self.strategies = factory.strategies()
        if "epoch" in factory.compressor:
            self.epoch = int(factory.compressor["epoch"])
        if factory.compressor.get("checkpoint_path"):
            self.checkpoint_path = factory.compressor["checkpoint_path"]
        return self

    def run(self):
        """Train ``epoch`` epochs with strategy callbacks; returns the
        (possibly wrapped/pruned) model."""
        if self.loss_fn is None or self.optimizer is None or \
                self.train_reader is None:
            raise ValueError("Compressor.run needs loss_fn, "
                             "train_optimizer and train_reader")
        context = Context(place=self.place, scope=self.scope,
                          train_graph=self.model,
                          optimizer=self.optimizer,
                          eval_func=self.eval_func)
        if self.teachers:
            context.put("teacher", self.teachers[0])
        for s in self.strategies:
            s.on_compression_begin(context)
        for epoch_id in range(self.epoch):
            context.epoch_id = epoch_id
            for s in self.strategies:
                s.on_epoch_begin(context)
            for batch_id, batch in enumerate(self.train_reader()):
                context.batch_id = batch_id
                from ..core.tensor import to_tensor

                tensors = tuple(to_tensor(np.asarray(b)) for b in batch)
                context.batch = tensors
                for s in self.strategies:
                    s.on_batch_begin(context)
                loss = self.loss_fn(context.train_graph, *tensors)
                for s in self.strategies:
                    for term in s.loss_terms(context):
                        loss = loss + term
                loss.backward()
                self.optimizer.step()
                self.optimizer.clear_grad()
                if batch_id % self.log_period == 0:
                    _logger.info(f"epoch {epoch_id} batch {batch_id} "
                                 f"loss {float(loss.numpy()):.4f}")
                for s in self.strategies:
                    s.on_batch_end(context)
            for s in self.strategies:
                s.on_epoch_end(context)
            if self.eval_func is not None:
                context.run_eval_graph()
            if self.checkpoint_path:
                from ..framework.io import save

                os.makedirs(self.checkpoint_path, exist_ok=True)
                save(context.train_graph.state_dict(),
                     os.path.join(self.checkpoint_path,
                                  f"epoch_{epoch_id}.pdparams"))
        for s in self.strategies:
            s.on_compression_end(context)
        self.model = context.train_graph
        self.context = context
        return self.model
