"""py2/py3 compatibility helpers (ref: python/paddle/compat.py).

Python 3-only now; kept so fluid-era code importing paddle.compat runs.
"""
from __future__ import annotations

import math

__all__ = ["long_type", "to_text", "to_bytes", "round", "floor_division",
           "get_exception_message"]

long_type = int


def to_text(obj, encoding="utf-8", inplace=False):
    """bytes/containers-of-bytes -> str (ref: compat.py to_text);
    ``inplace`` mutates list/dict containers like the reference."""
    if obj is None:
        return obj
    if isinstance(obj, bytes):
        return obj.decode(encoding)
    if isinstance(obj, list):
        if inplace:
            obj[:] = [to_text(o, encoding) for o in obj]
            return obj
        return [to_text(o, encoding) for o in obj]
    if isinstance(obj, set):
        new_set = {to_text(o, encoding) for o in obj}
        if inplace:
            obj.clear()
            obj |= new_set
            return obj
        return new_set
    if isinstance(obj, dict):
        new_d = {to_text(k, encoding): to_text(v, encoding)
                 for k, v in obj.items()}
        if inplace:
            obj.clear()
            obj.update(new_d)
            return obj
        return new_d
    return str(obj) if not isinstance(obj, str) else obj


def to_bytes(obj, encoding="utf-8", inplace=False):
    """str/containers-of-str -> bytes (ref: compat.py to_bytes);
    ``inplace`` mutates list/dict containers like the reference."""
    if obj is None:
        return obj
    if isinstance(obj, str):
        return obj.encode(encoding)
    if isinstance(obj, list):
        if inplace:
            obj[:] = [to_bytes(o, encoding) for o in obj]
            return obj
        return [to_bytes(o, encoding) for o in obj]
    if isinstance(obj, set):
        new_set = {to_bytes(o, encoding) for o in obj}
        if inplace:
            obj.clear()
            obj |= new_set
            return obj
        return new_set
    if isinstance(obj, dict):
        new_d = {to_bytes(k, encoding): to_bytes(v, encoding)
                 for k, v in obj.items()}
        if inplace:
            obj.clear()
            obj.update(new_d)
            return obj
        return new_d
    return obj


def round(x, d=0):  # noqa: A001 (reference name)
    """Python-2 style round-half-away-from-zero (ref: compat.py)."""
    p = 10 ** d
    if x > 0:
        return float(math.floor((x * p) + 0.5)) / p
    if x < 0:
        return float(math.ceil((x * p) - 0.5)) / p
    return 0.0


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)
