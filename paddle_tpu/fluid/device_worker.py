"""fluid.device_worker (ref: python/paddle/fluid/device_worker.py).

Reference DeviceWorkers generate the protobuf descriptions the C++
trainer threads execute (Hogwild lock-free CPU threads, DownpourSGD
parameter-server pulls/pushes, Section pipeline stages). In the XLA
design the Executor compiles the whole program into one fused
executable, so there are no per-thread worker descs to generate —
``_gen_worker_desc`` fills the (inert, documented) trainer_desc config
containers so reference driver scripts that wire
``TrainerFactory -> DeviceWorker -> trainer_desc`` run unmodified.
Parallel execution itself comes from the data-parallel Executor path
(static_/executor.py) and dist/ pipelines.
"""
from __future__ import annotations

__all__ = ["DeviceWorker", "Hogwild", "DownpourSGD", "DownpourSGDOPT",
           "Section", "DeviceWorkerFactory"]


class DeviceWorker:
    def __init__(self):
        self._infer = None
        self._fleet_desc = None
        self._program = None

    def _set_infer(self, infer=False):
        self._infer = infer

    def _set_fleet_desc(self, fleet_desc):
        self._fleet_desc = fleet_desc

    def _set_program(self, program):
        self._program = program

    def _gen_worker_desc(self, trainer_desc):
        raise NotImplementedError(
            "DeviceWorker is a base class; use Hogwild/DownpourSGD/Section")


class Hogwild(DeviceWorker):
    """Lock-free multithread CPU worker in the reference; here the name
    records that the program runs through the (single fused executable)
    Executor dataset loop."""

    def _gen_worker_desc(self, trainer_desc):
        trainer_desc.device_worker_name = "HogwildWorker"
        if self._infer:
            trainer_desc.hogwild_param = {"skip_ops": ["feed", "fetch"]}


class DownpourSGD(DeviceWorker):
    """Parameter-server pull/push worker (recorded descope §4b)."""

    def _gen_worker_desc(self, trainer_desc):
        trainer_desc.device_worker_name = "DownpourWorker"


class DownpourSGDOPT(DownpourSGD):
    def _gen_worker_desc(self, trainer_desc):
        trainer_desc.device_worker_name = "DownpourWorkerOpt"


class Section(DeviceWorker):
    """Pipeline-stage worker; the live pipeline engine is
    dist/pipeline.py (GPipe over shard_map + ppermute)."""

    def _gen_worker_desc(self, trainer_desc):
        trainer_desc.device_worker_name = "SectionWorker"


class DeviceWorkerFactory:
    def _create_device_worker(self, worker_type):
        classes = {c.__name__.lower(): c for c in
                   (Hogwild, DownpourSGD, DownpourSGDOPT, Section)}
        return classes[str(worker_type).lower()]()
