"""fluid.contrib.utils (ref: python/paddle/fluid/contrib/utils/).

Two members in the reference:
- hdfs_utils (hdfs_utils.py:35 HDFSClient): a subprocess wrapper over the
  ``hadoop fs`` CLI. Same design here — thin, real, and dependency-free;
  it errors clearly when no hadoop binary is on PATH.
- lookup_table_utils (lookup_table_utils.py:85): program surgery for the
  parameter-server sparse lookup tables. PS mode is a recorded descope
  (SURVEY §4b — ICI/SPMD subsumes it; sparse embeddings shard over the
  mesh via VocabParallelEmbedding), so these raise the descope error.
"""
from __future__ import annotations

import os
import shutil
import subprocess

__all__ = ["HDFSClient", "multi_download", "multi_upload", "getfilelist",
           "convert_dist_to_sparse_program",
           "load_persistables_for_increment",
           "load_persistables_for_inference"]


class HDFSClient:
    """ref hdfs_utils.py:35 — shells out to ``hadoop fs`` exactly like
    the reference (there via java_home/hadoop_home; here any ``hadoop``
    on PATH or an explicit ``hadoop_home``)."""

    def __init__(self, hadoop_home=None, configs=None):
        self._bin = (os.path.join(hadoop_home, "bin", "hadoop")
                     if hadoop_home else shutil.which("hadoop"))
        self._configs = []
        for k, v in (configs or {}).items():
            self._configs += ["-D", f"{k}={v}"]

    def _run(self, *args, check=True):
        if self._bin is None or not os.path.exists(self._bin):
            raise RuntimeError(
                "no hadoop binary found (PATH or hadoop_home); HDFSClient "
                "wraps the 'hadoop fs' CLI just like the reference "
                "hdfs_utils.py")
        cmd = [self._bin, "fs", *self._configs, *args]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if check and proc.returncode != 0:
            raise RuntimeError(
                f"hadoop fs {' '.join(args)} failed: {proc.stderr.strip()}")
        return proc

    def ls(self, path):
        proc = self._run("-ls", path)
        files = []
        for line in proc.stdout.splitlines():
            parts = line.split()
            if len(parts) >= 8:
                files.append(parts[-1])
        return files

    def lsr(self, path):
        proc = self._run("-ls", "-R", path)
        return [ln.split()[-1] for ln in proc.stdout.splitlines()
                if len(ln.split()) >= 8]

    def is_exist(self, path):
        return self._run("-test", "-e", path, check=False).returncode == 0

    def is_dir(self, path):
        return self._run("-test", "-d", path, check=False).returncode == 0

    def is_file(self, path):
        return self.is_exist(path) and not self.is_dir(path)

    def delete(self, path):
        return self._run("-rm", "-r", "-skipTrash", path).returncode == 0

    def rename(self, src, dst):
        return self._run("-mv", src, dst).returncode == 0

    def makedirs(self, path):
        return self._run("-mkdir", "-p", path).returncode == 0

    def upload(self, hdfs_path, local_path, overwrite=False, retry_times=5):
        args = ["-put"] + (["-f"] if overwrite else []) + \
            [local_path, hdfs_path]
        return self._run(*args).returncode == 0

    def download(self, hdfs_path, local_path, overwrite=False,
                 unzip=False):
        if os.path.exists(local_path):
            if not overwrite:
                raise ValueError(
                    f"local path {local_path!r} exists; pass "
                    "overwrite=True to replace it")
            if os.path.isdir(local_path):
                shutil.rmtree(local_path)
            else:
                os.remove(local_path)
        return self._run("-get", hdfs_path, local_path).returncode == 0


def getfilelist(path):
    """ref hdfs_utils.py:508 — local walk variant used by multi_*."""
    rlist = []
    for dirname, _, files in os.walk(path):
        for f in files:
            rlist.append(os.path.join(dirname, f))
    return rlist


def multi_download(client, hdfs_path, local_path, trainer_id, trainers,
                   multi_processes=5):
    """ref hdfs_utils.py:437 — this trainer downloads its round-robin
    share of the files under ``hdfs_path``."""
    files = client.lsr(hdfs_path)
    mine = [f for i, f in enumerate(sorted(files))
            if i % trainers == trainer_id]
    base = hdfs_path.rstrip("/") + "/"
    for f in mine:
        # keep the path relative to hdfs_path: same-named files in
        # different subdirs (a/part-00000, b/part-00000) must not collide
        rel = f[len(base):] if f.startswith(base) else os.path.basename(f)
        dst = os.path.join(local_path, rel)
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        client.download(f, dst)
    return mine


def multi_upload(client, hdfs_path, local_path, multi_processes=5,
                 overwrite=False, sync=True):
    """ref hdfs_utils.py:518."""
    client.makedirs(hdfs_path)
    uploaded = []
    for f in getfilelist(local_path):
        rel = os.path.relpath(f, local_path)
        dst = hdfs_path.rstrip("/") + "/" + rel
        rd = os.path.dirname(dst)
        if rd:
            client.makedirs(rd)
        client.upload(dst, f, overwrite=overwrite)
        uploaded.append(dst)
    return uploaded


def _ps_descoped(name):
    raise NotImplementedError(
        f"{name} is parameter-server lookup-table plumbing "
        "(ref contrib/utils/lookup_table_utils.py) — PS mode is a "
        "recorded descope (SURVEY §4b): on TPU, sparse embeddings shard "
        "over the mesh (VocabParallelEmbedding) and ICI collectives "
        "subsume the PS round-trips")


def convert_dist_to_sparse_program(program):
    _ps_descoped("convert_dist_to_sparse_program")


def load_persistables_for_increment(dirname, executor, program, *a, **k):
    _ps_descoped("load_persistables_for_increment")


def load_persistables_for_inference(dirname, executor, program, *a, **k):
    _ps_descoped("load_persistables_for_inference")
