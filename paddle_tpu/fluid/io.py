"""fluid.io (ref: python/paddle/fluid/io.py): the framework io surface
plus the fluid-era loaders, exactly as the reference re-exports
``reader.__all__`` from fluid/io.py:38."""
from __future__ import annotations

from ..framework.io import *  # noqa: F401,F403
from ..framework.io import (save_inference_model,  # noqa: F401
                            load_inference_model, save, load,
                            load_program_state, set_program_state,
                            save_checkpoint, load_checkpoint)
from ..io_.reader import (batch, shuffle, buffered, map_readers,  # noqa: F401
                          xmap_readers, chain, compose, firstn, cache,
                          DataFeeder)
from .reader import DataLoader, PyReader, GeneratorLoader  # noqa: F401
