"""fluid.reader submodule (ref: python/paddle/fluid/reader.py).

The reference module hosts the feeding loaders of the fluid era:
``DataLoader.from_generator`` (ref reader.py:179) and ``PyReader``
(ref reader.py:1064), both wrappers that move user generators into the
executor feed loop (there via C++ LoDTensor queues and a double-buffer
thread). On TPU the Executor compiles the whole program and feeds are
host numpy arrays, so the loaders reduce to honest generator adapters:
they batch samples, name the arrays after the feed_list variables, and
yield executor-ready feed dicts. The modern path is paddle.io.DataLoader
(io_/dataloader.py) with the native prefetch ring; these exist so
fluid-era scripts run unmodified.
"""
from __future__ import annotations

import numpy as np

from ..io_.dataloader import DataLoader as _ModernDataLoader
from ..static_.program import Variable

__all__ = ["DataLoader", "PyReader", "GeneratorLoader"]


def _feed_names(feed_list):
    names = []
    for v in feed_list or []:
        names.append(v.name if isinstance(v, Variable) else str(v))
    return names


class GeneratorLoader:
    """Generator-fed loader (ref reader.py:791 GeneratorLoader). Yields
    ``{name: np.ndarray}`` feed dicts for ``Executor.run``."""

    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False):
        self._names = _feed_names(feed_list)
        self._gen = None
        self._iterable = iterable
        self._return_list = return_list

    # -- decoration (ref GeneratorLoader.set_* / PyReader.decorate_*) ------
    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        def batches():
            buf = []
            for sample in reader():
                buf.append(sample if isinstance(sample, (list, tuple))
                           else (sample,))
                if len(buf) == batch_size:
                    yield [np.stack([np.asarray(s[i]) for s in buf])
                           for i in range(len(buf[0]))]
                    buf = []
            if buf and not drop_last:
                yield [np.stack([np.asarray(s[i]) for s in buf])
                       for i in range(len(buf[0]))]

        self._gen = batches
        return self

    def set_sample_list_generator(self, reader, places=None):
        def batches():
            for samples in reader():
                yield [np.stack([np.asarray(s[i]) for s in samples])
                       for i in range(len(samples[0]))]

        self._gen = batches
        return self

    def set_batch_generator(self, reader, places=None):
        def batches():
            for batch in reader():
                yield [np.asarray(a) for a in
                       (batch if isinstance(batch, (list, tuple))
                        else (batch,))]

        self._gen = batches
        return self

    # -- iteration ----------------------------------------------------------
    def __iter__(self):
        if self._gen is None:
            raise RuntimeError(
                "no generator set: call set_sample_generator / "
                "set_sample_list_generator / set_batch_generator first")
        for arrays in self._gen():
            if self._return_list:
                yield list(arrays)
            else:
                if len(arrays) != len(self._names):
                    raise ValueError(
                        f"generator yielded {len(arrays)} arrays but "
                        f"feed_list has {len(self._names)} variables "
                        f"({self._names})")
                yield dict(zip(self._names, arrays))

    def __call__(self):
        return iter(self)

    # non-iterable (start/reset) protocol degenerates to iteration here
    def start(self):
        return None

    def reset(self):
        return None


class DataLoader(_ModernDataLoader):
    """fluid.reader.DataLoader: the modern loader plus the fluid-era
    ``from_generator`` constructor (ref reader.py:179)."""

    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False, drop_last=True):
        return GeneratorLoader(feed_list=feed_list, capacity=capacity,
                               use_double_buffer=use_double_buffer,
                               iterable=iterable, return_list=return_list)

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        """ref reader.py:437 — loader over a ``fluid.dataset`` slot-file
        Dataset (iterates its already-batched feed dicts); a paddle.io
        map-style Dataset gets the modern loader."""
        from .dataset import DatasetBase

        if isinstance(dataset, DatasetBase):
            return _SlotDatasetLoader(dataset, drop_last)
        return _ModernDataLoader(dataset, drop_last=drop_last)


class _SlotDatasetLoader:
    """Loader face over a fluid.dataset slot-file Dataset: each
    iteration restarts the dataset's batch stream."""

    def __init__(self, dataset, drop_last):
        self._dataset = dataset
        self._drop_last = drop_last

    def __iter__(self):
        return self._dataset.iter_batches(drop_last=self._drop_last)

    __call__ = __iter__


class PyReader(GeneratorLoader):
    """ref reader.py:1064 — the deprecated generator reader; identical
    adapter with the decorate_* method names."""

    decorate_sample_generator = GeneratorLoader.set_sample_generator
    decorate_sample_list_generator = GeneratorLoader.set_sample_list_generator
    decorate_batch_generator = GeneratorLoader.set_batch_generator
