"""fluid.communicator (ref: python/paddle/fluid/communicator.py).

The reference Communicator is the async parameter-server send/recv
thread pool used by distribute_transpiler mode. Parameter-server mode
is a recorded descope (SURVEY §4b): on TPU pods, gradient exchange is
an XLA collective inside the compiled step, so there is no background
communication to start or stop. The class keeps the reference's
lifecycle surface so PS-era drivers run unmodified; start/stop manage
only the running flag.
"""
from __future__ import annotations

import warnings

__all__ = ["Communicator"]


class Communicator:
    def __init__(self, program, mode=None, kwargs=None, envs=None):
        warnings.warn(
            "fluid.communicator.Communicator is parameter-server "
            "machinery; on TPU, gradient exchange happens via XLA "
            "collectives inside the compiled step — start()/stop() "
            "manage only a flag here", Warning)
        self._program = program
        self._mode = mode
        self._running = False

    def start(self):
        self._running = True

    def stop(self):
        self._running = False

    def is_running(self):
        return self._running
