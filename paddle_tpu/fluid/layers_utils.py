"""fluid.layers.utils (ref: python/paddle/fluid/layers/utils.py) —
the nest/structure helpers user RNN cells and decoders program against
(map_structure over state pytrees, flatten/pack round-trips). Dict
traversal follows the reference's sorted-key order.
"""
from __future__ import annotations

__all__ = ["is_sequence", "flatten", "pack_sequence_as", "map_structure",
           "assert_same_structure", "to_sequence", "sequence_like"]


def is_sequence(seq):
    """ref: utils.py:70 — dict/list/tuple (but not str) count."""
    if isinstance(seq, dict):
        return True
    return isinstance(seq, (list, tuple)) and not isinstance(seq, str)


def _yield_flat(nest):
    if isinstance(nest, dict):
        for k in sorted(nest):
            yield from _yield_flat(nest[k])
    elif is_sequence(nest):
        for item in nest:
            yield from _yield_flat(item)
    else:
        yield nest


def flatten(nest):
    """ref: utils.py:113 — leaves in deterministic order."""
    return list(_yield_flat(nest)) if is_sequence(nest) else [nest]


def _packed_iter(structure, flat, idx):
    if isinstance(structure, dict):
        out = {}
        for k in sorted(structure):
            out[k], idx = _packed_iter(structure[k], flat, idx)
        return out, idx
    if is_sequence(structure):
        items = []
        for s in structure:
            v, idx = _packed_iter(s, flat, idx)
            items.append(v)
        return (tuple(items) if isinstance(structure, tuple)
                else items), idx
    return flat[idx], idx + 1


def pack_sequence_as(structure, flat_sequence):
    """ref: utils.py:162 — inverse of flatten for the same structure."""
    if not is_sequence(structure):
        if len(flat_sequence) != 1:
            raise ValueError("structure is a scalar but "
                             f"len(flat_sequence)={len(flat_sequence)}")
        return flat_sequence[0]
    packed, used = _packed_iter(structure, list(flat_sequence), 0)
    if used != len(flat_sequence):
        raise ValueError(
            f"could not pack {len(flat_sequence)} leaves into the "
            f"structure (used {used})")
    return packed


def map_structure(func, *structure):
    """ref: utils.py:184 — apply func leaf-wise across structures."""
    flats = [flatten(s) for s in structure]
    n = len(flats[0])
    if any(len(f) != n for f in flats):
        raise ValueError("structures have different leaf counts")
    results = [func(*leaves) for leaves in zip(*flats)]
    return pack_sequence_as(structure[0], results)


def assert_same_structure(nest1, nest2, check_types=True):
    """ref: utils.py:244."""
    f1, f2 = flatten(nest1), flatten(nest2)
    if len(f1) != len(f2):
        raise ValueError(
            f"structures differ: {len(f1)} vs {len(f2)} leaves")
    if check_types:
        def skeleton(n):
            if isinstance(n, dict):
                return {k: skeleton(v) for k, v in n.items()}
            if is_sequence(n):
                return [skeleton(v) for v in n]
            return None

        if skeleton(nest1) != skeleton(nest2):
            raise TypeError("structure types differ")


def to_sequence(nest):
    return nest if is_sequence(nest) else [nest]


def sequence_like(instance, args):
    return pack_sequence_as(instance, list(args))
