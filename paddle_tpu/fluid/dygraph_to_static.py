"""dygraph-to-static surface (ref: python/paddle/fluid/dygraph/
dygraph_to_static/ — program_translator.py, ast_transformer.py,
variable_trans_func.py, static_analysis.py, loop_transformer.py,
break_continue_transformer.py).

Design note: the reference converts dygraph code to graph mode by
REWRITING PYTHON SOURCE — gast transforms turn ``if``/``for``/``break``
into cond/while/select ops, then the rewritten function builds a
ProgramDesc. The XLA-era conversion is TRACING: eager layer code is
jax-traceable by design (core/dispatch.py), so ``to_static`` compiles
the same function directly and ``lax.cond/scan/while_loop`` (via
ops.control_flow) express data-dependent control flow. The public
surface (ProgramTranslator, convert_to_static, declarative) is
therefore fully functional here, while the AST-rewrite internals
(DygraphToStaticAst, the transformer visitors) survive as documented
design-replacement stubs — there is no source rewriting to do.
"""
from __future__ import annotations

import inspect
import textwrap

import numpy as np

__all__ = [
    "ProgramTranslator", "convert_to_static",
    "convert_function_with_cache", "declarative",
    "DygraphToStaticAst", "BreakContinueTransformer", "LoopTransformer",
    "NameVisitor", "AstNodeWrapper", "NodeVarType",
    "StaticAnalysisVisitor", "to_static_variable_gast_node",
    "create_static_variable_gast_node", "data_layer_not_check",
]

_AST_NOTE = (
    "source-rewrite transformers are replaced by tracing here: eager "
    "code is jax-traceable, so to_static/jit compile it directly; "
    "express data-dependent control flow with ops.control_flow "
    "(lax.cond / while_loop / scan)")


def convert_to_static(dyfunc):
    """ref: ast_transformer.py:237 — return a static-executable version
    of ``dyfunc``. Tracing-based: the compiled StaticFunction."""
    from ..framework.jit import to_static

    return to_static(dyfunc)


_FUNC_CACHE = {}


def convert_function_with_cache(dygraph_func):
    """ref: program_translator.py:75 — cached conversion."""
    key = getattr(dygraph_func, "__wrapped__", dygraph_func)
    if key not in _FUNC_CACHE:
        _FUNC_CACHE[key] = convert_to_static(dygraph_func)
    return _FUNC_CACHE[key]


def declarative(fn):
    """ref: dygraph/jit.py @declarative — mark a function for static
    compilation. The translator flag is consulted at CALL time (the
    reference contract: ProgramTranslator().enable(False) makes
    decorated functions run eagerly for debugging); keyword arguments
    also route to the eager path, since the compiled StaticFunction is
    positional-only."""
    import functools

    compiled = convert_to_static(fn)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if kwargs or not ProgramTranslator().enable_declarative:
            return fn(*args, **kwargs)
        return compiled(*args)

    return wrapper


class ProgramTranslator:
    """ref: program_translator.py:231 — the singleton front for
    dygraph→static conversion."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance._initialized = False
        return cls._instance

    def __init__(self):
        if self._initialized:
            return
        self._initialized = True
        self.enable_declarative = True
        self._program_cache = {}

    @classmethod
    def get_instance(cls):
        return cls()

    @classmethod
    def reset(cls):
        cls._instance = None

    def enable(self, enable_declarative):
        """ref: toggle whether declarative functions actually compile
        (False = run eagerly, for debugging)."""
        self.enable_declarative = bool(enable_declarative)

    def get_output(self, dygraph_func, *args, **kwargs):
        """Run ``dygraph_func`` statically (compiled) and return its
        outputs; eager passthrough when disabled — or when keyword
        arguments are passed (the compiled StaticFunction is
        positional-only)."""
        if not self.enable_declarative or kwargs:
            return dygraph_func(*args, **kwargs)
        return convert_function_with_cache(dygraph_func)(*args)

    def get_func(self, dygraph_func):
        if not self.enable_declarative:
            return dygraph_func
        return convert_function_with_cache(dygraph_func)

    def get_program(self, dygraph_func, *args, **kwargs):
        """Trace ``dygraph_func`` into (main_program, startup_program,
        inputs, outputs) — the tracing analog of the reference's AST
        build."""
        from .. import static_ as _static
        from ..static_ import Program, program_guard
        from ..static_.program import data

        key = (id(getattr(dygraph_func, "__wrapped__", dygraph_func)),
               tuple((np.asarray(a).shape, str(np.asarray(a).dtype))
                     for a in args),
               tuple(sorted((k, repr(v)) for k, v in kwargs.items())))
        if key in self._program_cache:
            return self._program_cache[key]
        was_static = _static.in_static_mode()
        if not was_static:
            _static.enable_static()
        try:
            main, startup = Program(), Program()
            with program_guard(main, startup):
                feed_vars = [
                    data(f"translator_x{i}",
                         list(np.asarray(a).shape),
                         dtype=str(np.asarray(a).dtype))
                    for i, a in enumerate(args)]
                outs = dygraph_func(*feed_vars, **kwargs)
            outputs = list(outs) if isinstance(outs, (list, tuple)) \
                else [outs]
            result = (main, startup, feed_vars, outputs)
            self._program_cache[key] = result
            return result
        finally:
            if not was_static:
                _static.disable_static()

    def get_code(self, dygraph_func):
        """The static-mode source. Tracing does not rewrite source, so
        this is the (dedented) original — which IS the code the static
        build runs."""
        return textwrap.dedent(inspect.getsource(
            getattr(dygraph_func, "__wrapped__", dygraph_func)))

    def get_program_cache(self):
        return self._program_cache

    def save_inference_model(self, dirname, feed=None, fetch=None):
        """Persist the most recently traced program as an inference
        bundle (ref: program_translator.py:362)."""
        from ..framework.io import save_inference_model
        from ..static_ import Executor

        if not self._program_cache:
            raise RuntimeError("no traced program yet — call get_output "
                               "or get_program first")
        main, startup, inputs, outputs = \
            list(self._program_cache.values())[-1]
        feed_vars = [inputs[i] for i in feed] if feed else inputs
        fetch_vars = [outputs[i] for i in fetch] if fetch else outputs
        save_inference_model(dirname, feed_vars, fetch_vars, Executor(),
                             program=main)
        return dirname


def data_layer_not_check(name, shape, dtype="float32", lod_level=0):
    """ref: variable_trans_func.py — a data var whose dims may be None
    (variable length); None records as the 1 placeholder here, like
    static.data."""
    from ..static_.program import data

    return data(name, [1 if s is None else s for s in shape],
                dtype=dtype, lod_level=lod_level)


def to_static_variable_gast_node(name):
    raise NotImplementedError(_AST_NOTE)


def create_static_variable_gast_node(name):
    raise NotImplementedError(_AST_NOTE)


class DygraphToStaticAst:
    """ref: ast_transformer.py DygraphToStaticAst (gast rewriter)."""

    def get_static_ast(self, root):
        raise NotImplementedError(_AST_NOTE)


class _AstStub:
    def __init__(self, *a, **k):
        raise NotImplementedError(_AST_NOTE)


class BreakContinueTransformer(_AstStub):
    """ref: break_continue_transformer.py."""


class LoopTransformer(_AstStub):
    """ref: loop_transformer.py."""


class NameVisitor(_AstStub):
    """ref: loop_transformer.py NameVisitor."""


class AstNodeWrapper(_AstStub):
    """ref: static_analysis.py."""


class StaticAnalysisVisitor(_AstStub):
    """ref: static_analysis.py."""


class NodeVarType:
    """ref: static_analysis.py NodeVarType — the type-lattice constants
    (kept real: they are plain enums some user tooling imports)."""

    ERROR = -1
    UNKNOWN = 0
    STATEMENT = 1
    CALLABLE = 2
    NONE = 100
    BOOLEAN = 101
    INT = 102
    FLOAT = 103
    STRING = 104
    TENSOR = 200
    NUMPY_NDARRAY = 201
    PADDLE_DYGRAPH_API = 300
    PADDLE_CONTROL_IF = 301
    PADDLE_CONTROL_WHILE = 302
    PADDLE_CONTROL_FOR = 303
