"""fluid.layer_helper (ref: python/paddle/fluid/layer_helper.py) — the
parameter/variable factory custom user layers are written against::

    helper = LayerHelper("my_scale", **locals())
    w = helper.create_parameter(attr=helper.param_attr, shape=[d],
                                dtype="float32")
    out = my_math_on(w, x)          # functional ops record the graph

In the reference, append_op writes OpDescs by slot name; here ops
record themselves when the functional API runs (static tracing), so
the factory half (create_parameter / create_variable_for_type_inference
/ input handling / append_activation / append_bias_op) is the live
surface, and append_op additionally accepts any op in the kernel
registry with positional inputs.
"""
from __future__ import annotations

import numpy as np

from ..nn.param_attr import ParamAttr
from ..utils import unique_name

__all__ = ["LayerHelper"]


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.layer_type = layer_type
        self.kwargs = kwargs
        name = kwargs.get("name")
        self._prefix = name if name is not None else layer_type

    # -- naming / attrs (ref: layer_helper_base.py) -------------------------
    @property
    def name(self):
        return self._prefix

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr"))

    def multiple_param_attr(self, length):
        attr = self.param_attr
        return [attr] * length

    # -- inputs -------------------------------------------------------------
    def multiple_input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name, [])
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        return list(inputs)

    def input(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError(
                f"{self.layer_type} expects one input, got {len(inputs)}")
        return inputs[0]

    def input_dtype(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        if not inputs:
            return "float32"
        x = inputs[0]
        return str(getattr(getattr(x, "_data", x), "dtype", "float32"))

    # -- factories ----------------------------------------------------------
    def create_parameter(self, attr, shape, dtype="float32",
                         is_bias=False, default_initializer=None):
        """A fresh parameter through the Layer machinery — registers the
        persistable var + scope value in static mode, a live Parameter
        eagerly (ref: layer_helper_base.py create_parameter)."""
        from ..nn.layer import Layer

        holder = Layer(name_scope=self._prefix)
        return holder.create_parameter(
            shape, attr=attr, dtype=dtype, is_bias=is_bias,
            default_initializer=default_initializer)

    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        """A temp output var in the current program (static), or a
        placeholder name eagerly (functional ops make their own
        outputs)."""
        from ..core import dispatch

        tracer = dispatch.current_tracer()
        if tracer is None:
            return None  # eager: the op's own return is the variable
        blk = tracer.program.current_block()
        return blk.create_var(
            name=unique_name.generate(f"{self._prefix}.tmp"),
            shape=(), dtype=dtype, stop_gradient=stop_gradient)

    def create_variable(self, *args, **kwargs):
        return self.create_variable_for_type_inference(
            kwargs.get("dtype", "float32"))

    def create_global_variable(self, shape, dtype, persistable=False,
                               *a, **k):
        from .. import ops as _ops

        return _ops.zeros(list(shape), dtype=dtype)

    # -- op appending -------------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        """Run a registry kernel over positional inputs (dict insertion
        order); the result lands in outputs' first slot when given.
        Reference ops absent from the registry raise by name so the
        porter knows which functional API to call instead."""
        from ..core import dispatch
        from ..ops._base import OP_REGISTRY

        if type not in OP_REGISTRY:
            raise NotImplementedError(
                f"op '{type}' has no registered kernel; call the "
                f"functional API (paddle_tpu.ops / fluid.layers) instead "
                "of LayerHelper.append_op")
        args = []
        for v in (inputs or {}).values():
            args.extend(v if isinstance(v, (list, tuple)) else [v])
        out = dispatch.apply(type, OP_REGISTRY[type], *args,
                             **(attrs or {}))
        return out

    def append_activation(self, input_var=None, act=None):
        act = act if act is not None else self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, dict):
            act = act.get("type")
        from ..nn import functional as F

        return getattr(F, act)(input_var)

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        bias_attr = self.bias_attr
        if bias_attr is False:
            return input_var
        size = int(np.prod(input_var.shape[dim_start:dim_end]))
        b = self.create_parameter(bias_attr, [size],
                                  dtype=self.input_dtype(), is_bias=True)
        return input_var + b

    # -- misc ---------------------------------------------------------------
    def set_variable_initializer(self, var, initializer):
        var.initializer = initializer
        return var

    @property
    def main_program(self):
        from ..static_.program import default_main_program

        return default_main_program()

    @property
    def startup_program(self):
        from ..static_.program import default_startup_program

        return default_startup_program()
