"""TrainerDesc / DataFeedDesc surface (ref: python/paddle/fluid/
trainer_desc.py, data_feed_desc.py).

In the reference these are protobuf builders consumed by the C++
multi-threaded trainer (device_worker / data_feed) of the parameter-
server era — infrastructure recorded as a descope in SURVEY §4b (XLA owns
the execution loop; the io_/runtime shard readers own ingestion). The
classes survive as plain config containers so fluid-era scripts that
build them keep importing; anything that would launch the PS trainer
raises with the descope pointer.

The dataset-driven training path itself is NOT descoped: use
``fluid.DatasetFactory`` (fluid/dataset.py — real MultiSlot file
readers) with ``Executor.train_from_dataset`` / ``infer_from_dataset``,
which consume the same slot files through the compiled program.
"""
from __future__ import annotations

__all__ = ["TrainerDesc", "MultiTrainer", "DistMultiTrainer",
           "PipelineTrainer", "DataFeedDesc"]

_DESCOPE = ("the parameter-server trainer stack is descoped (SURVEY "
            "§4b); use Executor / ParallelExecutor or dist.fleet")


class TrainerDesc:
    """Config container; ``_gen_trainer_desc`` etc. are proto-era hooks."""

    def __init__(self):
        self.proto_desc = {"class_name": type(self).__name__,
                           "thread_num": 1, "fetch_config": {}}
        self._program = None
        self._infer = False

    def set_thread(self, n):
        self.proto_desc["thread_num"] = int(n)

    def set_program(self, program):
        self._program = program

    def set_infer(self, infer):
        self._infer = bool(infer)

    def _set_use_cvm(self, use_cvm):
        self.proto_desc["use_cvm"] = bool(use_cvm)

    def set_fetch_var_and_info(self, fetch_vars, fetch_info, print_period):
        self.proto_desc["fetch_config"] = {
            "vars": [getattr(v, "name", str(v)) for v in fetch_vars],
            "info": list(fetch_info), "print_period": int(print_period)}

    def _desc(self):
        return dict(self.proto_desc)


class MultiTrainer(TrainerDesc):
    def run(self, *a, **k):
        raise NotImplementedError(_DESCOPE)


class DistMultiTrainer(TrainerDesc):
    def run(self, *a, **k):
        raise NotImplementedError(_DESCOPE)


class PipelineTrainer(TrainerDesc):
    def run(self, *a, **k):
        raise NotImplementedError(_DESCOPE)


class DataFeedDesc:
    """ref: data_feed_desc.py — wraps a text-proto describing slots. Here
    a minimal parser keeps the slot/batch accessors working for configs
    written against the reference."""

    def __init__(self, proto_file=None):
        self.proto_desc = {"name": "MultiSlotDataFeed", "batch_size": 32,
                           "slots": []}
        if proto_file is not None:
            import os

            if os.path.exists(proto_file):
                with open(proto_file) as f:
                    self._parse(f.read())

    def _parse(self, text):
        import re

        m = re.search(r"batch_size\s*:\s*(\d+)", text)
        if m:
            self.proto_desc["batch_size"] = int(m.group(1))
        # only names INSIDE slots{...} blocks are slots (the top-level
        # name: "MultiSlotDataFeed" is the feed class, not a slot)
        for block in re.finditer(r"slots\s*\{([^}]*)\}", text):
            sm = re.search(r"name\s*:\s*\"([^\"]+)\"", block.group(1))
            if sm:
                self.proto_desc["slots"].append(
                    {"name": sm.group(1), "is_used": False})

    def set_batch_size(self, n):
        self.proto_desc["batch_size"] = int(n)

    def set_dense_slots(self, names):
        for s in self.proto_desc["slots"]:
            if s["name"] in names:
                s["is_dense"] = True

    def set_use_slots(self, names):
        for s in self.proto_desc["slots"]:
            if s["name"] in names:
                s["is_used"] = True

    def desc(self):
        return str(self.proto_desc)
