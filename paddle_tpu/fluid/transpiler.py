"""fluid.transpiler compatibility surface.

Ref: python/paddle/fluid/transpiler/__init__.py — DistributeTranspiler,
DistributeTranspilerConfig, HashName, RoundRobin, memory_optimize,
release_memory.

The parameter-server transpilation itself is a recorded descope
(SURVEY §4b): on TPU pods, SPMD collectives over ICI subsume the PS
mode, and ``fleet.init`` + DistributedStrategy is the supported path.
The config/dispatcher objects are real so PS-era recipes can construct
them and be routed to collective mode with a clear error at transpile
time; memory passes are no-ops because XLA owns buffer planning.
"""
from __future__ import annotations

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "HashName", "RoundRobin", "memory_optimize", "release_memory"]


class DistributeTranspilerConfig:
    """ref: distribute_transpiler.py DistributeTranspilerConfig."""

    def __init__(self):
        self.slice_var_up = True
        self.split_method = None
        self.min_block_size = 8192
        self.enable_dc_asgd = False
        self.mode = "pserver"
        self.print_log = False
        self.wait_port = True
        self.sync_mode = True
        self.runtime_split_send_recv = False
        self.geo_sgd_mode = False
        self.geo_sgd_need_push_nums = 100


class _PSDispatcher:
    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    def reset(self):
        self._step = 0

    def eps(self):
        return self._eps


class HashName(_PSDispatcher):
    """ref: ps_dispatcher.py HashName: var -> endpoint by a STABLE name
    hash (builtin hash() is salted per process, which would give each
    trainer a different var->endpoint mapping)."""

    def dispatch(self, varlist):
        import zlib

        out = []
        for v in varlist:
            name = v if isinstance(v, str) else v.name
            out.append(self._eps[zlib.crc32(name.encode())
                                 % len(self._eps)])
        return out


class RoundRobin(_PSDispatcher):
    """ref: ps_dispatcher.py RoundRobin: vars -> endpoints cyclically."""

    def dispatch(self, varlist):
        out = []
        for _ in varlist:
            out.append(self._eps[self._step % len(self._eps)])
            self._step += 1
        return out


class DistributeTranspiler:
    """ref: distribute_transpiler.py DistributeTranspiler. Construction
    succeeds (recipes build it unconditionally); ``transpile`` raises
    with the collective-mode route — there are no CPU parameter shards
    to host on a TPU pod (SURVEY §4b)."""

    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()

    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint="127.0.0.1:6174"):
        raise NotImplementedError(
            "parameter-server transpilation is descoped on TPU "
            "(SURVEY §4b): sparse tables shard over the mesh "
            "(VocabParallelEmbedding) and gradients ride XLA "
            "collectives. Use fleet.init(strategy) / "
            "dist.init_parallel_env() instead.")

    def get_trainer_program(self, wait_port=True):
        raise NotImplementedError("call transpile() first (descoped)")

    def get_pserver_program(self, endpoint):
        raise NotImplementedError("call transpile() first (descoped)")


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    """ref: memory_optimization_transpiler.py memory_optimize.

    The REWRITE half stays descoped (XLA's buffer assignment already
    performs liveness-based buffer reuse on the whole fused program —
    the reference pass's var-reuse rewrites would be dead weight), but
    the ANALYSIS half is real now: the same versioned-liveness walk the
    reference pass ran (``paddle_tpu.analysis.dataflow`` /
    ``.memory``) returns the Program's predicted peak-HBM
    ``MemoryEstimate``, and ``print_log=True`` prints the summary the
    reference VLOG'd. ``None`` in, ``None`` out (source compat with
    callers that pass no program)."""
    if input_program is None:
        return None
    from ..analysis import memory as _memory

    try:
        est = _memory.estimate_entry(input_program)
    except Exception:  # deprecated-API callers relied on the no-op
        return None    # never failing; an analysis miss must not either
    if print_log:
        po = (f" at op#{est.peak_op[0]} ({est.peak_op[1]})"
              if est.peak_op else "")
        print(f"memory_optimize: predicted peak {est.peak_bytes} B "
              f"(args {est.arg_bytes} + outputs {est.output_bytes} + "
              f"temps {est.temp_peak_bytes}{po}); buffer reuse is "
              "delegated to XLA buffer assignment")
    return est


def release_memory(input_program, skip_opt_set=None):
    """ref: release_memory — no-op; XLA owns buffer lifetimes."""
    return None


class Collective:
    """Collective-mode transpiler base (ref: transpiler/collective.py:36).

    The reference rewrites the program: inserts c_broadcast into startup
    (rank-0 weight sync) and c_allreduce_sum + scale into main. Here the
    same contract — "after transpile, running main_program IS data-
    parallel" — is delivered by marking the program for the Executor's
    SPMD path (static_/executor.py): the batch axis shards over the
    ('data',) mesh, persistables stay replicated (XLA broadcasts them at
    compile time, subsuming the startup c_broadcast), and XLA inserts
    the gradient all-reduce over ICI.
    """

    def __init__(self, nrings=1):
        self.nrings = nrings
        self.rank = 0
        self.nranks = 1

    def transpile(self, startup_program=None, main_program=None, rank=0,
                  endpoints="127.0.0.1:6174", current_endpoint=None,
                  wait_port=True):
        from ..static_.program import default_main_program

        if isinstance(endpoints, str):
            endpoints = endpoints.split(",")
        self.rank = int(rank)
        self.nranks = len(endpoints)
        self.main_program = main_program or default_main_program()
        self.startup_program = startup_program
        self._transpile_main_program()
        return self

    def _transpile_main_program(self):
        self.main_program._transpiled_dp = True
        self.main_program.bump()


class GradAllReduce(Collective):
    """ref: collective.py:178 — synchronous gradient all-reduce DP."""


class LocalSGD(Collective):
    """ref: collective.py:270 — run k local steps, then average params.

    The param-averaging round is the same SPMD all-reduce with the
    params (not grads) as the reduced tensors; with the one-program
    design each executed step is already globally synchronous, so the
    local-step window collapses to 1 (documented semantic difference:
    equivalent at convergence, no stale-weights window).
    """
