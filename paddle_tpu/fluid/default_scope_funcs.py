"""fluid.default_scope_funcs (ref: python/paddle/fluid/default_scope_funcs.py).

A thread-local stack of Scopes; the top is the current scope. The
reference keeps C++ Scope kids alive via new_scope/drop_kids — here a
Scope is a plain name→array dict (static_/program.py Scope), so local
scopes are independent dicts pushed/popped on the stack.
"""
from __future__ import annotations

import threading

from ..static_.program import Scope

__all__ = [
    "get_cur_scope", "enter_local_scope", "leave_local_scope",
    "var", "find_var", "scoped_function",
]

__tl_scope__ = threading.local()


def get_cur_scope():
    """Current (top-of-stack) scope; the bottom scope is created lazily."""
    stack = getattr(__tl_scope__, "cur_scope", None)
    if stack is None:
        stack = __tl_scope__.cur_scope = []
    if not stack:
        stack.append(Scope())
    return stack[-1]


def enter_local_scope():
    get_cur_scope()  # materialize the parent
    __tl_scope__.cur_scope.append(Scope())


def leave_local_scope():
    __tl_scope__.cur_scope.pop()
    get_cur_scope().drop_kids()


def var(name):
    """Create (or fetch) a variable slot in the current scope."""
    scope = get_cur_scope()
    if scope.find_var(name) is None:
        scope.set(name, None)
    return scope.var(name)


def find_var(name):
    return get_cur_scope().find_var(name)


def scoped_function(func):
    """Invoke ``func`` inside a fresh local scope."""
    enter_local_scope()
    try:
        return func()
    finally:
        leave_local_scope()
