"""fluid.install_check (ref: python/paddle/fluid/install_check.py).

``run_check()`` trains one step of a 2x2 linear model end-to-end (fwd,
bwd, SGD update) on whatever backend jax resolved to, proving the stack
is importable and executable.
"""
from __future__ import annotations

__all__ = ["run_check"]


def run_check():
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import nn, optim

    class _SimpleLayer(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(2, 2)

        def forward(self, x):
            return self.fc(x).sum()

    model = _SimpleLayer()
    opt = optim.SGD(learning_rate=0.1, parameters=model.parameters())
    x = pt.to_tensor(np.ones((2, 2), np.float32))
    loss = model(x)
    loss.backward()
    opt.step()
    opt.clear_grad()
    print("Your paddle_tpu is installed successfully! Backend:",
          pt.get_device())
    return True
