"""fluid.data_feeder submodule (ref: python/paddle/fluid/data_feeder.py).

The reference module carries the DataFeeder class plus the dtype/type
validators that nearly every fluid layer calls on its inputs. Here the
validators are real (they raise the same error classes with the same
spirit of message) and DataFeeder is the shared io_ implementation.
"""
from __future__ import annotations

import numpy as np

from ..core.dtype import convert_dtype as _to_jax_dtype
from ..core.tensor import Tensor
from ..io_.reader import DataFeeder  # noqa: F401
from ..static_.program import Variable

__all__ = ["DataFeeder", "convert_dtype", "check_variable_and_dtype",
           "check_type", "check_dtype"]


def convert_dtype(dtype):
    """Normalize any dtype spelling to the canonical string name
    (ref data_feeder.py:30 — there VarDesc enum -> str)."""
    return str(np.dtype(_to_jax_dtype(dtype)))


def check_type(input, input_name, expected_type, op_name, extra_message=""):
    """ref data_feeder.py:83."""
    if not isinstance(input, expected_type):
        raise TypeError(
            f"The type of '{input_name}' in {op_name} must be "
            f"{expected_type}, but received {type(input)}. {extra_message}")


def check_dtype(input_dtype, input_name, expected_dtype, op_name,
                extra_message=""):
    """ref data_feeder.py:99."""
    canon = convert_dtype(input_dtype)
    expected = tuple(convert_dtype(d) for d in (
        expected_dtype if isinstance(expected_dtype, (list, tuple))
        else (expected_dtype,)))
    if canon not in expected:
        raise TypeError(
            f"The data type of '{input_name}' in {op_name} must be one of "
            f"{list(expected)}, but received {canon}. {extra_message}")


def check_variable_and_dtype(input, input_name, expected_dtype, op_name,
                             extra_message=""):
    """ref data_feeder.py:74 — input must be a Variable/Tensor of one of
    the expected dtypes."""
    check_type(input, input_name, (Variable, Tensor), op_name,
               extra_message)
    dtype = getattr(input, "dtype", None)
    if dtype is None and getattr(input, "_data", None) is not None:
        dtype = input._data.dtype
    check_dtype(dtype, input_name, expected_dtype, op_name, extra_message)
