"""paddle_tpu.fluid — compatibility namespace for fluid-era code.

Ref: the ``import paddle.fluid as fluid`` surface of the reference
(python/paddle/fluid/__init__.py). Code written against the reference —
``fluid.data``, ``fluid.layers.fc``, ``fluid.Executor``,
``exe.run(program, feed, fetch_list)``, ``fluid.optimizer.SGD`` — runs
here unchanged; every symbol maps onto the TPU-native implementation
(one jitted executable per program, XLA collectives, dense sequence
layouts).
"""
import contextlib as _contextlib

from .. import static_ as _static
from ..static_.program import (Program,  # noqa: F401
                               default_main_program,
                               default_startup_program, global_scope)
from ..static_.program import program_guard as _program_guard


@_contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """fluid-era code treats static graph as the default mode and never
    calls enable_static(); this guard switches it on for the block."""
    import paddle_tpu as _pt

    was_static = _static.in_static_mode()
    if not was_static:
        _pt.enable_static()
    try:
        with _program_guard(main_program, startup_program):
            yield
    finally:
        if not was_static:
            _pt.disable_static()
from ..static_.executor import Executor  # noqa: F401
from ..static_.program import (Scope, scope_guard,  # noqa: F401
                               name_scope)
from ..static_ import backward  # noqa: F401
from ..static_.backward import gradients, append_backward  # noqa: F401
from ..static_.program import Variable  # noqa: F401
from ..framework.jit import to_static  # noqa: F401
from . import io  # noqa: F401  (framework io + fluid-era loaders)
from ..framework.io import (save_inference_model,  # noqa: F401
                            load_inference_model)
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from .dataset import (DatasetFactory, InMemoryDataset,  # noqa: F401
                      QueueDataset)
from . import data_feeder  # noqa: F401
from ..core.device import CPUPlace, CUDAPlace, TPUPlace  # noqa: F401

CUDAPinnedPlace = CPUPlace  # host-staging place: plain host memory here


def is_compiled_with_cuda():
    return False  # TPU build — recipes branch to the collective path
from .. import optim as optimizer  # noqa: F401
from ..nn.param_attr import ParamAttr  # noqa: F401
from ..nn import initializer  # noqa: F401
from ..optim import clip  # noqa: F401
from ..optim import regularizer  # noqa: F401
from ..io_ import reader as io_reader
from ..io_.reader import DataFeeder  # noqa: F401
from ..utils import unique_name  # noqa: F401
from ..nn.param_attr import WeightNormParamAttr  # noqa: F401
from ..framework.io import (save, load, load_program_state,  # noqa: F401
                            set_program_state)
from .lod_tensor import (LoDTensor, LoDTensorArray,  # noqa: F401
                         create_lod_tensor, create_random_int_lodtensor)
from . import average  # noqa: F401
from . import evaluator  # noqa: F401
from . import profiler  # noqa: F401
from . import install_check  # noqa: F401
from ..nn.layer import Layer  # noqa: F401
from .. import metrics  # noqa: F401
from .. import nn as _nn
from ..nn import nets  # noqa: F401
from . import layers  # noqa: F401
from . import dygraph  # noqa: F401

# top-level conveniences the reference exposes on fluid itself
data = _static.data
one_hot = layers.one_hot  # ref: fluid/input.py re-exported at top level
embedding = layers.embedding
Tensor = LoDTensor  # ref: fluid/__init__.py:92 "Tensor = LoDTensor"
from ..core.tensor import Tensor as VarBase  # noqa: E402  (dygraph tensor)
from ..optim import lr as learning_rate_decay  # noqa: E402
from .transpiler import HashName, RoundRobin  # noqa: F401,E402
from . import trainer_desc  # noqa: E402
from .trainer_desc import (TrainerDesc, MultiTrainer,  # noqa: F401,E402
                           DistMultiTrainer, PipelineTrainer, DataFeedDesc)
enable_dygraph = lambda place=None: None  # dygraph (eager) is the default
disable_dygraph = lambda: None
in_dygraph_mode = lambda: not _static.in_static_mode() \
    if hasattr(_static, "in_static_mode") else True

__all__ = [
    "Program", "program_guard", "default_main_program",
    "default_startup_program", "global_scope", "Executor", "DataFeeder",
    "CPUPlace", "CUDAPlace", "TPUPlace", "CUDAPinnedPlace", "ParamAttr",
    "optimizer", "initializer", "clip", "regularizer", "layers",
    "dygraph", "nets", "metrics", "io", "data", "save_inference_model",
    "load_inference_model", "to_static", "Layer", "contrib",
    "cpu_places", "cuda_places", "cuda_pinned_places", "device_guard",
    "get_flags", "set_flags", "load_op_library", "require_version",
    "incubate", "transpiler", "DistributeTranspiler",
    "DistributeTranspilerConfig", "memory_optimize", "release_memory",
    "backward", "gradients", "scope_guard", "name_scope", "Scope",
    "unique_name", "LoDTensor", "LoDTensorArray", "Tensor",
    "create_lod_tensor", "create_random_int_lodtensor", "one_hot",
    "embedding", "average", "evaluator", "profiler", "install_check",
    "WeightNormParamAttr", "save", "load", "load_program_state",
    "set_program_state", "save_dygraph", "load_dygraph",
    "CompiledProgram", "BuildStrategy", "ExecutionStrategy",
    "ParallelExecutor", "enable_dygraph", "disable_dygraph",
    "in_dygraph_mode", "is_compiled_with_cuda", "Variable", "VarBase",
    "append_backward", "HashName", "RoundRobin", "learning_rate_decay",
    "TrainerDesc", "MultiTrainer", "DistMultiTrainer", "PipelineTrainer",
    "DataFeedDesc", "trainer_desc",
]


class CompiledProgram:  # re-export with the fluid name
    def __new__(cls, *args, **kwargs):
        from ..static_.compiler import CompiledProgram as CP

        return CP(*args, **kwargs)


from ..static_.compiler import (BuildStrategy,  # noqa: F401,E402
                                ExecutionStrategy, ParallelExecutor)
from .dygraph import (save_dygraph, load_dygraph)  # noqa: F401,E402


# -- places / flags / version (ref: fluid/framework.py __all__) --------------


def cpu_places(device_count=None):
    import os

    n = device_count or int(os.environ.get("CPU_NUM", "1"))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """Accelerator places: TPU chips here (ref: framework.py
    cuda_places). Sized by jax.local_device_count()."""
    import jax

    ids = device_ids if device_ids is not None else \
        range(jax.local_device_count())
    return [TPUPlace(i) for i in ids]


def cuda_pinned_places(device_count=None):
    """Host staging places: the runtime arena owns pinned buffers
    (runtime/cc); exposed as CPU places."""
    return cpu_places(device_count)


@_contextlib.contextmanager
def device_guard(device=None):
    """ref: framework.py device_guard. Op-level device pinning inside
    one XLA program is owned by the compiler; the guard is accepted for
    source compatibility."""
    yield


_FLAGS = {}


def set_flags(flags):
    """ref: framework.py set_flags (FLAGS_* gflags). XLA equivalents
    ride XLA_FLAGS; unknown keys are stored for get_flags round-trip."""
    _FLAGS.update(dict(flags))


def get_flags(flags):
    keys = [flags] if isinstance(flags, str) else list(flags)
    return {k: _FLAGS.get(k) for k in keys}


def load_op_library(path):
    raise NotImplementedError(
        "custom C++ op libraries are CUDA-era; TPU custom kernels are "
        "pallas (ops/pallas/) or host callbacks (fluid.layers.py_func)")


def require_version(min_version, max_version=None):
    """ref: framework.py require_version: raise unless the installed
    version is inside [min_version, max_version]."""
    import paddle_tpu as _pt

    def parse(v):
        import re

        parts = []
        for x in str(v).split(".")[:3]:
            m = re.match(r"\d+", x)  # "0-rc0" / "0rc1" -> 0
            parts.append(int(m.group(0)) if m else 0)
        return tuple(parts)

    cur = parse(_pt.__version__)
    if parse(min_version) > cur or (
            max_version is not None and parse(max_version) < cur):
        raise Exception(
            f"paddle_tpu version {_pt.__version__} outside required "
            f"[{min_version}, {max_version or 'any'}]")
    return _pt.__version__
from . import contrib  # noqa: F401,E402
from . import incubate  # noqa: F401,E402
from . import transpiler  # noqa: F401,E402
from .transpiler import (DistributeTranspiler,  # noqa: F401,E402
                         DistributeTranspilerConfig, memory_optimize,
                         release_memory)
from . import log_helper  # noqa: F401,E402
from . import wrapped_decorator  # noqa: F401,E402
from . import default_scope_funcs  # noqa: F401,E402
from . import communicator  # noqa: F401,E402
from . import device_worker  # noqa: F401,E402
from . import trainer_factory  # noqa: F401,E402
from . import fleet_utils  # noqa: F401,E402
from . import distributed  # noqa: F401,E402
from .trainer_factory import FetchHandler  # noqa: F401,E402

# fluid-era submodule names (fluid.core / framework / executor / ...):
# installed last so every implementation they alias already exists
import sys as _sys  # noqa: E402

from . import modules_compat as _modules_compat  # noqa: E402

_modules_compat.install(_sys.modules[__name__])
