"""paddle_tpu.fluid — compatibility namespace for fluid-era code.

Ref: the ``import paddle.fluid as fluid`` surface of the reference
(python/paddle/fluid/__init__.py). Code written against the reference —
``fluid.data``, ``fluid.layers.fc``, ``fluid.Executor``,
``exe.run(program, feed, fetch_list)``, ``fluid.optimizer.SGD`` — runs
here unchanged; every symbol maps onto the TPU-native implementation
(one jitted executable per program, XLA collectives, dense sequence
layouts).
"""
import contextlib as _contextlib

from .. import static_ as _static
from ..static_.program import (Program,  # noqa: F401
                               default_main_program,
                               default_startup_program, global_scope)
from ..static_.program import program_guard as _program_guard


@_contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """fluid-era code treats static graph as the default mode and never
    calls enable_static(); this guard switches it on for the block."""
    import paddle_tpu as _pt

    was_static = _static.in_static_mode()
    if not was_static:
        _pt.enable_static()
    try:
        with _program_guard(main_program, startup_program):
            yield
    finally:
        if not was_static:
            _pt.disable_static()
from ..static_.executor import Executor  # noqa: F401
from ..framework.jit import to_static  # noqa: F401
from ..framework import io  # noqa: F401
from ..framework.io import (save_inference_model,  # noqa: F401
                            load_inference_model)
from ..core.device import CPUPlace, CUDAPlace, TPUPlace  # noqa: F401

CUDAPinnedPlace = CPUPlace  # host-staging place: plain host memory here


def is_compiled_with_cuda():
    return False  # TPU build — recipes branch to the collective path
from .. import optim as optimizer  # noqa: F401
from ..nn.param_attr import ParamAttr  # noqa: F401
from ..nn import initializer  # noqa: F401
from ..optim import clip  # noqa: F401
from ..optim import regularizer  # noqa: F401
from ..io_ import reader as io_reader
from ..io_.reader import DataFeeder  # noqa: F401
from ..nn.layer import Layer  # noqa: F401
from .. import metrics  # noqa: F401
from .. import nn as _nn
from ..nn import nets  # noqa: F401
from . import layers  # noqa: F401
from . import dygraph  # noqa: F401

# top-level conveniences the reference exposes on fluid itself
data = _static.data
enable_dygraph = lambda place=None: None  # dygraph (eager) is the default
disable_dygraph = lambda: None
in_dygraph_mode = lambda: not _static.in_static_mode() \
    if hasattr(_static, "in_static_mode") else True

__all__ = [
    "Program", "program_guard", "default_main_program",
    "default_startup_program", "global_scope", "Executor", "DataFeeder",
    "CPUPlace", "CUDAPlace", "TPUPlace", "CUDAPinnedPlace", "ParamAttr",
    "optimizer", "initializer", "clip", "regularizer", "layers",
    "dygraph", "nets", "metrics", "io", "data", "save_inference_model",
    "load_inference_model", "to_static", "Layer",
]


class CompiledProgram:  # re-export with the fluid name
    def __new__(cls, *args, **kwargs):
        from ..static_.compiler import CompiledProgram as CP

        return CP(*args, **kwargs)
