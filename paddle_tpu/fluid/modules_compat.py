"""Fluid-era submodule names (ref: python/paddle/fluid/__init__.py:34-84).

Reference scripts import these as MODULES — ``from paddle.fluid import
core``, ``fluid.framework.default_main_program()``,
``fluid.executor.global_scope()`` — rather than through the flat fluid
namespace. Each is a small real module face over the implementation's
actual home, registered under the dotted name so both attribute access
and ``import paddle_tpu.fluid.core`` work. Built here (not as .py files)
because several names collide with top-level packages
(paddle_tpu.framework is the jit/io package; fluid.framework is the
Program surface).
"""
from __future__ import annotations

import sys
import types

__all__ = ["install"]


def _module(name, doc, members):
    m = types.ModuleType(name, doc)
    for k, v in members.items():
        setattr(m, k, v)
    sys.modules[name] = m
    return m


def install(fluid_pkg):
    """Create and attach the compat submodules onto the fluid package."""
    base = fluid_pkg.__name__

    from ..core.device import CPUPlace, CUDAPlace, TPUPlace
    from ..core.tensor import Tensor
    from ..static_ import (CompiledProgram, BuildStrategy,
                           ExecutionStrategy, Executor, Program, Scope,
                           Variable, default_main_program,
                           default_startup_program, global_scope,
                           name_scope, scope_guard)
    from ..static_.compiler import ParallelExecutor
    from ..static_.executor import FetchHandler as _FetchHandler
    from ..inference.analysis import (AnalysisConfig as _AnalysisConfig,
                                      PaddleTensor as _PaddleTensor,
                                      create_paddle_predictor as
                                      _create_paddle_predictor)
    from .lod_tensor import (LoDTensor, LoDTensorArray, create_lod_tensor,
                             create_random_int_lodtensor)

    framework = _module(
        base + ".framework",
        "fluid.framework (ref framework.py): the Program surface.",
        dict(Program=Program, Variable=Variable, Parameter=Variable,
             default_main_program=default_main_program,
             default_startup_program=default_startup_program,
             # the PACKAGE-level guard (it switches static mode on for
             # the block — fluid-era scripts never call enable_static)
             program_guard=fluid_pkg.program_guard,
             name_scope=name_scope,
             in_dygraph_mode=fluid_pkg.in_dygraph_mode,
             grad_var_name=lambda name: name + "@GRAD",
             cpu_places=fluid_pkg.cpu_places,
             cuda_places=fluid_pkg.cuda_places))

    executor = _module(
        base + ".executor",
        "fluid.executor (ref executor.py).",
        dict(Executor=Executor, global_scope=global_scope,
             scope_guard=scope_guard, Scope=Scope,
             FetchHandler=_FetchHandler))

    compiler = _module(
        base + ".compiler",
        "fluid.compiler (ref compiler.py).",
        dict(CompiledProgram=CompiledProgram, BuildStrategy=BuildStrategy,
             ExecutionStrategy=ExecutionStrategy))

    parallel_executor = _module(
        base + ".parallel_executor",
        "fluid.parallel_executor (ref parallel_executor.py).",
        dict(ParallelExecutor=ParallelExecutor,
             BuildStrategy=BuildStrategy,
             ExecutionStrategy=ExecutionStrategy))

    core = _module(
        base + ".core",
        "fluid.core (ref pybind core.so): the handful of types fluid-era "
        "scripts reach into core for; everything is the Python-level "
        "equivalent (there is deliberately no C++ binding layer here — "
        "XLA owns the device runtime).",
        dict(LoDTensor=LoDTensor, LoDTensorArray=LoDTensorArray,
             CPUPlace=CPUPlace, CUDAPlace=CUDAPlace,
             CUDAPinnedPlace=CPUPlace, TPUPlace=TPUPlace, Scope=Scope,
             VarBase=Tensor,
             is_compiled_with_cuda=lambda: False,
             get_cuda_device_count=lambda: 0,
             # deploy-script entry (ref pybind/inference_api.cc)
             AnalysisConfig=_AnalysisConfig,
             create_paddle_predictor=_create_paddle_predictor,
             PaddleTensor=_PaddleTensor))

    from .trainer_desc import DataFeedDesc

    data_feed_desc = _module(
        base + ".data_feed_desc",
        "fluid.data_feed_desc (ref data_feed_desc.py).",
        dict(DataFeedDesc=DataFeedDesc))

    from .incubate import (MultiSlotDataGenerator,
                           MultiSlotStringDataGenerator)

    data_generator = _module(
        base + ".data_generator",
        "fluid.data_generator (ref incubate/data_generator).",
        dict(MultiSlotDataGenerator=MultiSlotDataGenerator,
             MultiSlotStringDataGenerator=MultiSlotStringDataGenerator))

    def _distribute_lookup_table(*a, **k):
        raise NotImplementedError(
            "distribute_lookup_table is parameter-server plumbing "
            "(SURVEY §4b descope); sparse embeddings shard over the mesh "
            "via VocabParallelEmbedding")

    distribute_lookup_table = _module(
        base + ".distribute_lookup_table",
        "fluid.distribute_lookup_table (PS-era; recorded descope).",
        dict(find_distributed_lookup_table=_distribute_lookup_table))

    from .contrib_trainer import Inferencer

    inferencer = _module(
        base + ".inferencer",
        "fluid.inferencer (ref inferencer.py — moved to contrib; the "
        "real class lives in fluid/contrib_trainer.py).",
        dict(Inferencer=Inferencer))

    def monkey_patch_variable():
        """ref math_op_patch.py: Variables here already carry operator
        methods natively — nothing to patch."""
        return None

    def monkey_patch_varbase():
        return None

    mods = dict(framework=framework, executor=executor, compiler=compiler,
                parallel_executor=parallel_executor, core=core,
                data_feed_desc=data_feed_desc,
                data_generator=data_generator,
                distribute_lookup_table=distribute_lookup_table,
                inferencer=inferencer)
    for k, v in mods.items():
        setattr(fluid_pkg, k, v)
    fluid_pkg.monkey_patch_variable = monkey_patch_variable
    fluid_pkg.monkey_patch_varbase = monkey_patch_varbase
    # ref fluid/__init__.py:72: fleet is re-exported from incubate
    fluid_pkg.fleet = fluid_pkg.incubate.fleet
    # module-import spellings for the attribute-aliased submodules
    # (from paddle.fluid import initializer / backward / clip / ... as
    # MODULES — their homes live elsewhere in the package tree)
    for alias in ("initializer", "regularizer", "clip", "metrics",
                  "nets", "optimizer", "unique_name", "backward"):
        mod = getattr(fluid_pkg, alias)
        sys.modules[f"{base}.{alias}"] = mod

    # fluid.layer_helper / fluid.input / fluid.layers.utils (real homes:
    # fluid/layer_helper.py, fluid/layers_utils.py)
    from . import layer_helper as _lh  # noqa: F401 (registers the file)
    from . import layers_utils as _lu

    sys.modules[base + ".layers.utils"] = _lu
    fluid_pkg.layers.utils = _lu
    input_face = _module(
        base + ".input",
        "ref: fluid/input.py (embedding, one_hot).",
        dict(embedding=fluid_pkg.layers.embedding,
             one_hot=fluid_pkg.layers.one_hot))
    fluid_pkg.input = input_face

    mods.update(_install_contrib_faces(fluid_pkg))
    mods.update(_install_incubate_faces(fluid_pkg))
    return mods


def _install_contrib_faces(fluid_pkg):
    """contrib submodule spellings (ref: fluid/contrib/__init__.py):
    mixed_precision is the static AMP package; its real home here is
    paddle_tpu/amp (+ amp/static_decorator.py for the fluid decorate)."""
    base = fluid_pkg.__name__

    from ..amp.lists import AutoMixedPrecisionLists
    from ..amp.static_decorator import OptimizerWithMixedPrecision, decorate

    mp_decorator = _module(
        base + ".contrib.mixed_precision.decorator",
        "ref: mixed_precision/decorator.py.",
        dict(decorate=decorate,
             OptimizerWithMixedPrecision=OptimizerWithMixedPrecision))
    from ..amp import lists as _amp_lists

    fp16_lists = _module(
        base + ".contrib.mixed_precision.fp16_lists",
        "ref: mixed_precision/fp16_lists.py (home: paddle_tpu/amp/lists).",
        dict(AutoMixedPrecisionLists=AutoMixedPrecisionLists,
             white_list=_amp_lists.WHITE_LIST,
             black_list=_amp_lists.BLACK_LIST))
    mixed_precision = _module(
        base + ".contrib.mixed_precision",
        "ref: fluid/contrib/mixed_precision/__init__.py.",
        dict(decorate=decorate,
             OptimizerWithMixedPrecision=OptimizerWithMixedPrecision,
             AutoMixedPrecisionLists=AutoMixedPrecisionLists,
             decorator=mp_decorator, fp16_lists=fp16_lists))
    contrib = fluid_pkg.contrib
    contrib.mixed_precision = mixed_precision

    # contrib trainer-era high-level API (ref: contrib/trainer.py,
    # contrib/inferencer.py; home: fluid/contrib_trainer.py)
    from . import contrib_trainer as _ct

    trainer_face = _module(
        base + ".contrib.trainer",
        "ref: fluid/contrib/trainer.py.",
        dict(Trainer=_ct.Trainer, BeginEpochEvent=_ct.BeginEpochEvent,
             EndEpochEvent=_ct.EndEpochEvent,
             BeginStepEvent=_ct.BeginStepEvent,
             EndStepEvent=_ct.EndStepEvent,
             CheckpointConfig=_ct.CheckpointConfig))
    inferencer_face = _module(
        base + ".contrib.inferencer",
        "ref: fluid/contrib/inferencer.py.",
        dict(Inferencer=_ct.Inferencer))
    for name in ("Trainer", "BeginEpochEvent", "EndEpochEvent",
                 "BeginStepEvent", "EndStepEvent", "CheckpointConfig",
                 "Inferencer"):
        setattr(contrib, name, getattr(_ct, name))
    contrib.trainer = trainer_face
    contrib.inferencer = inferencer_face

    # contrib.decoder beam-search stack (ref: contrib/decoder/;
    # home: fluid/contrib_decoder.py)
    from . import contrib_decoder as _cd

    bsd_face = _module(
        base + ".contrib.decoder.beam_search_decoder",
        "ref: contrib/decoder/beam_search_decoder.py.",
        dict(InitState=_cd.InitState, StateCell=_cd.StateCell,
             TrainingDecoder=_cd.TrainingDecoder,
             BeamSearchDecoder=_cd.BeamSearchDecoder))
    decoder_face = _module(
        base + ".contrib.decoder",
        "ref: fluid/contrib/decoder/.",
        dict(beam_search_decoder=bsd_face, InitState=_cd.InitState,
             StateCell=_cd.StateCell, TrainingDecoder=_cd.TrainingDecoder,
             BeamSearchDecoder=_cd.BeamSearchDecoder))
    contrib.decoder = decoder_face
    for name in ("InitState", "StateCell", "TrainingDecoder"):
        setattr(contrib, name, getattr(_cd, name))
    # NB: contrib re-exports the decoder BeamSearchDecoder in the
    # reference too, shadowing none of layers' dynamic-decode API
    contrib.BeamSearchDecoder = _cd.BeamSearchDecoder

    # contrib.slim package tree (ref: fluid/contrib/slim/; homes:
    # paddle_tpu/slim/compressor.py + quant/passes.py)
    from .. import slim as _sl

    slim_faces = {
        "core.compressor": dict(Compressor=_sl.Compressor,
                                Context=_sl.Context),
        "core.config": dict(ConfigFactory=_sl.ConfigFactory),
        "core.strategy": dict(Strategy=_sl.Strategy),
        "prune.pruner": dict(StructurePruner=_sl.StructurePruner,
                             Pruner=_sl.Pruner,
                             MagnitudePruner=_sl.MagnitudePruner),
        "prune.prune_strategy": dict(
            PruneStrategy=_sl.PruneStrategy,
            UniformPruneStrategy=_sl.UniformPruneStrategy,
            SensitivePruneStrategy=_sl.SensitivePruneStrategy),
        "prune.auto_prune_strategy": dict(
            AutoPruneStrategy=_sl.AutoPruneStrategy),
        "distillation.distiller": dict(
            L2Distiller=_sl.L2Distiller, FSPDistiller=_sl.FSPDistiller,
            SoftLabelDistiller=_sl.SoftLabelDistiller),
        "distillation.distillation_strategy": dict(
            DistillationStrategy=_sl.DistillationStrategy),
        "quantization.quantization_pass": dict(
            QuantizationTransformPass=_sl.QuantizationTransformPass,
            QuantizationFreezePass=_sl.QuantizationFreezePass,
            ConvertToInt8Pass=_sl.ConvertToInt8Pass,
            TransformForMobilePass=_sl.TransformForMobilePass,
            OutScaleForTrainingPass=_sl.OutScaleForTrainingPass,
            OutScaleForInferencePass=_sl.OutScaleForInferencePass,
            AddQuantDequantPass=_sl.AddQuantDequantPass),
        "quantization.quantization_strategy": dict(
            QuantizationStrategy=_sl.QuantizationStrategy),
        "quantization.mkldnn_post_training_strategy": dict(
            MKLDNNPostTrainingQuantStrategy=(
                _sl.MKLDNNPostTrainingQuantStrategy)),
        "quantization.qat_int8_mkldnn_pass": dict(
            QatInt8MkldnnPass=_sl.compressor.QatInt8MkldnnPass),
        "quantization.qat2_int8_mkldnn_pass": dict(
            Qat2Int8MkldnnPass=_sl.compressor.Qat2Int8MkldnnPass),
        "graph.graph_wrapper": dict(GraphWrapper=_sl.GraphWrapper,
                                    VarWrapper=_sl.VarWrapper,
                                    OpWrapper=_sl.OpWrapper),
        "graph.executor": dict(SlimGraphExecutor=_sl.SlimGraphExecutor),
        "searcher.controller": dict(
            EvolutionaryController=_sl.EvolutionaryController,
            SAController=_sl.SAController),
        "nas.light_nas_strategy": dict(
            LightNASStrategy=_sl.LightNASStrategy),
        "nas.search_space": dict(SearchSpace=_sl.SearchSpace),
        "nas.controller_server": dict(
            ControllerServer=_sl.ControllerServer),
        "nas.search_agent": dict(SearchAgent=_sl.SearchAgent),
    }
    pkg_mods = {}
    for dotted, members in slim_faces.items():
        top, leaf = dotted.split(".")
        leaf_mod = _module(f"{base}.contrib.slim.{dotted}",
                           f"ref: fluid/contrib/slim/{dotted}.py.",
                           members)
        pkg = pkg_mods.get(top)
        if pkg is None:
            pkg = pkg_mods[top] = _module(
                f"{base}.contrib.slim.{top}",
                f"ref: fluid/contrib/slim/{top}/.", {})
        setattr(pkg, leaf, leaf_mod)
        for k, v in members.items():
            setattr(pkg, k, v)
    slim_face = _module(
        base + ".contrib.slim",
        "ref: fluid/contrib/slim/ (home: paddle_tpu/slim).",
        dict(Compressor=_sl.Compressor, **pkg_mods))
    contrib.slim = slim_face
    contrib.Compressor = _sl.Compressor

    # contrib.quantize (ref: fluid/contrib/quantize/quantize_transpiler)
    qt_face = _module(
        base + ".contrib.quantize.quantize_transpiler",
        "ref: fluid/contrib/quantize/quantize_transpiler.py.",
        dict(QuantizeTranspiler=_sl.QuantizeTranspiler))
    quantize_face = _module(
        base + ".contrib.quantize",
        "ref: fluid/contrib/quantize/.",
        dict(quantize_transpiler=qt_face,
             QuantizeTranspiler=_sl.QuantizeTranspiler))
    contrib.quantize = quantize_face
    contrib.QuantizeTranspiler = _sl.QuantizeTranspiler

    return {"contrib.mixed_precision": mixed_precision,
            "contrib.trainer": trainer_face,
            "contrib.decoder": decoder_face,
            "contrib.slim": slim_face}


def _install_incubate_faces(fluid_pkg):
    """Deep incubate.fleet.* spellings (ref: fluid/incubate/fleet/...).

    The implementation homes are fluid/incubate.py, fluid/fleet_utils.py
    and dist/fleet.py; these faces give the reference's package paths.
    The fleet face forwards unknown attributes to the fleet singleton so
    the import-system's parent-attribute clobber (importing
    ...incubate.fleet replaces the singleton attr with this module) is
    harmless."""
    base = fluid_pkg.__name__
    inc = fluid_pkg.incubate

    role_maker = _module(
        base + ".incubate.fleet.base.role_maker",
        "ref: incubate/fleet/base/role_maker.py.",
        dict(Role=inc.Role, RoleMakerBase=inc.RoleMakerBase,
             UserDefinedRoleMaker=inc.UserDefinedRoleMaker,
             UserDefinedCollectiveRoleMaker=(
                 inc.UserDefinedCollectiveRoleMaker),
             PaddleCloudRoleMaker=inc.PaddleCloudRoleMaker,
             MPISymetricRoleMaker=inc.MPISymetricRoleMaker,
             GeneralRoleMaker=inc.GeneralRoleMaker))
    fleet_base = _module(
        base + ".incubate.fleet.base",
        "ref: incubate/fleet/base/.",
        dict(role_maker=role_maker))

    collective = _module(
        base + ".incubate.fleet.collective",
        "ref: incubate/fleet/collective/__init__.py.",
        dict(fleet=inc.fleet,
             CollectiveOptimizer=inc.CollectiveOptimizer,
             DistributedStrategy=inc.CollectiveDistributedStrategy))

    from . import fleet_utils as _fu

    hdfs = _module(
        base + ".incubate.fleet.utils.hdfs",
        "ref: incubate/fleet/utils/hdfs.py (home: fluid/contrib_utils).",
        dict(HDFSClient=_fu.HDFSClient))
    fleet_util_mod = _module(
        base + ".incubate.fleet.utils.fleet_util",
        "ref: incubate/fleet/utils/fleet_util.py.",
        dict(FleetUtil=_fu.FleetUtil))
    utils_mod = _module(
        base + ".incubate.fleet.utils.utils",
        "ref: incubate/fleet/utils/utils.py.",
        dict(program_type_trans=_fu.program_type_trans,
             check_saved_vars_try_dump=_fu.check_saved_vars_try_dump,
             parse_program=_fu.parse_program,
             check_pruned_program_vars=_fu.check_pruned_program_vars,
             graphviz=_fu.graphviz))
    fleet_utils = _module(
        base + ".incubate.fleet.utils",
        "ref: incubate/fleet/utils/.",
        dict(hdfs=hdfs, fleet_util=fleet_util_mod, utils=utils_mod,
             HDFSClient=_fu.HDFSClient, FleetUtil=_fu.FleetUtil))

    distributed_strategy = _module(
        base + ".incubate.fleet.parameter_server.distribute_transpiler"
        ".distributed_strategy",
        "ref: parameter_server/distribute_transpiler/distributed_strategy"
        ".py.",
        dict(TrainerRuntimeConfig=inc.TrainerRuntimeConfig,
             DistributedStrategy=inc.PSDistributedStrategy,
             SyncStrategy=inc.SyncStrategy,
             AsyncStrategy=inc.AsyncStrategy,
             HalfAsyncStrategy=inc.HalfAsyncStrategy,
             GeoStrategy=inc.GeoStrategy,
             StrategyFactory=inc.StrategyFactory))
    dt_mod = _module(
        base + ".incubate.fleet.parameter_server.distribute_transpiler",
        "ref: parameter_server/distribute_transpiler/ (PS fleet mode is "
        "the recorded §4b descope; the strategy configs are live).",
        dict(fleet=inc.fleet, distributed_strategy=distributed_strategy))
    optimizer_factory = _module(
        base + ".incubate.fleet.parameter_server.pslib.optimizer_factory",
        "ref: parameter_server/pslib/optimizer_factory.py.",
        dict(DistributedAdam=inc.DistributedAdam,
             FLEET_GLOBAL_DICT=inc.FLEET_GLOBAL_DICT))
    pslib = _module(
        base + ".incubate.fleet.parameter_server.pslib",
        "ref: parameter_server/pslib/ (recorded §4b descope).",
        dict(fleet=inc.fleet, optimizer_factory=optimizer_factory))
    parameter_server = _module(
        base + ".incubate.fleet.parameter_server",
        "ref: incubate/fleet/parameter_server/.",
        dict(distribute_transpiler=dt_mod, pslib=pslib))

    fleet_face = _module(
        base + ".incubate.fleet",
        "ref: incubate/fleet/ — forwards to the fleet singleton.",
        dict(base=fleet_base, collective=collective, utils=fleet_utils,
             parameter_server=parameter_server))
    fleet_face.__getattr__ = lambda name: getattr(inc.fleet, name)

    # dygraph_to_static faces (ref: fluid/dygraph/dygraph_to_static/;
    # home: fluid/dygraph_to_static.py)
    from . import dygraph_to_static as _d2s

    d2s_faces = {}
    for leaf, members in {
        "program_translator": dict(
            ProgramTranslator=_d2s.ProgramTranslator,
            convert_function_with_cache=_d2s.convert_function_with_cache),
        "ast_transformer": dict(
            DygraphToStaticAst=_d2s.DygraphToStaticAst,
            convert_to_static=_d2s.convert_to_static),
        "loop_transformer": dict(LoopTransformer=_d2s.LoopTransformer,
                                 NameVisitor=_d2s.NameVisitor),
        "break_continue_transformer": dict(
            BreakContinueTransformer=_d2s.BreakContinueTransformer),
        "static_analysis": dict(
            AstNodeWrapper=_d2s.AstNodeWrapper,
            NodeVarType=_d2s.NodeVarType,
            StaticAnalysisVisitor=_d2s.StaticAnalysisVisitor),
        "variable_trans_func": dict(
            to_static_variable_gast_node=(
                _d2s.to_static_variable_gast_node),
            create_static_variable_gast_node=(
                _d2s.create_static_variable_gast_node),
            data_layer_not_check=_d2s.data_layer_not_check),
    }.items():
        d2s_faces[leaf] = _module(
            f"{base}.dygraph.dygraph_to_static.{leaf}",
            f"ref: dygraph/dygraph_to_static/{leaf}.py.", members)
    d2s_pkg = _module(
        base + ".dygraph.dygraph_to_static",
        "ref: fluid/dygraph/dygraph_to_static/.",
        dict(ProgramTranslator=_d2s.ProgramTranslator,
             convert_to_static=_d2s.convert_to_static, **d2s_faces))
    jit_face = _module(
        base + ".dygraph.jit",
        "ref: fluid/dygraph/jit.py (declarative).",
        dict(declarative=_d2s.declarative,
             TracedLayer=fluid_pkg.dygraph.TracedLayer))
    fluid_pkg.dygraph.dygraph_to_static = d2s_pkg
    fluid_pkg.dygraph.jit = jit_face

    # fluid.transpiler.collective spelling (classes live in
    # fluid/transpiler.py)
    from . import transpiler as _tr

    tr_collective = _module(
        base + ".transpiler.collective",
        "ref: transpiler/collective.py.",
        dict(Collective=_tr.Collective, GradAllReduce=_tr.GradAllReduce,
             LocalSGD=_tr.LocalSGD))
    _tr.collective = tr_collective

    return {"incubate.fleet": fleet_face}
