"""Fluid-era submodule names (ref: python/paddle/fluid/__init__.py:34-84).

Reference scripts import these as MODULES — ``from paddle.fluid import
core``, ``fluid.framework.default_main_program()``,
``fluid.executor.global_scope()`` — rather than through the flat fluid
namespace. Each is a small real module face over the implementation's
actual home, registered under the dotted name so both attribute access
and ``import paddle_tpu.fluid.core`` work. Built here (not as .py files)
because several names collide with top-level packages
(paddle_tpu.framework is the jit/io package; fluid.framework is the
Program surface).
"""
from __future__ import annotations

import sys
import types

__all__ = ["install"]


def _module(name, doc, members):
    m = types.ModuleType(name, doc)
    for k, v in members.items():
        setattr(m, k, v)
    sys.modules[name] = m
    return m


def install(fluid_pkg):
    """Create and attach the compat submodules onto the fluid package."""
    base = fluid_pkg.__name__

    from ..core.device import CPUPlace, CUDAPlace, TPUPlace
    from ..core.tensor import Tensor
    from ..static_ import (CompiledProgram, BuildStrategy,
                           ExecutionStrategy, Executor, Program, Scope,
                           Variable, default_main_program,
                           default_startup_program, global_scope,
                           name_scope, scope_guard)
    from ..static_.compiler import ParallelExecutor
    from .lod_tensor import (LoDTensor, LoDTensorArray, create_lod_tensor,
                             create_random_int_lodtensor)

    framework = _module(
        base + ".framework",
        "fluid.framework (ref framework.py): the Program surface.",
        dict(Program=Program, Variable=Variable, Parameter=Variable,
             default_main_program=default_main_program,
             default_startup_program=default_startup_program,
             # the PACKAGE-level guard (it switches static mode on for
             # the block — fluid-era scripts never call enable_static)
             program_guard=fluid_pkg.program_guard,
             name_scope=name_scope,
             in_dygraph_mode=fluid_pkg.in_dygraph_mode,
             grad_var_name=lambda name: name + "@GRAD",
             cpu_places=fluid_pkg.cpu_places,
             cuda_places=fluid_pkg.cuda_places))

    executor = _module(
        base + ".executor",
        "fluid.executor (ref executor.py).",
        dict(Executor=Executor, global_scope=global_scope,
             scope_guard=scope_guard, Scope=Scope))

    compiler = _module(
        base + ".compiler",
        "fluid.compiler (ref compiler.py).",
        dict(CompiledProgram=CompiledProgram, BuildStrategy=BuildStrategy,
             ExecutionStrategy=ExecutionStrategy))

    parallel_executor = _module(
        base + ".parallel_executor",
        "fluid.parallel_executor (ref parallel_executor.py).",
        dict(ParallelExecutor=ParallelExecutor,
             BuildStrategy=BuildStrategy,
             ExecutionStrategy=ExecutionStrategy))

    core = _module(
        base + ".core",
        "fluid.core (ref pybind core.so): the handful of types fluid-era "
        "scripts reach into core for; everything is the Python-level "
        "equivalent (there is deliberately no C++ binding layer here — "
        "XLA owns the device runtime).",
        dict(LoDTensor=LoDTensor, LoDTensorArray=LoDTensorArray,
             CPUPlace=CPUPlace, CUDAPlace=CUDAPlace,
             CUDAPinnedPlace=CPUPlace, TPUPlace=TPUPlace, Scope=Scope,
             VarBase=Tensor,
             is_compiled_with_cuda=lambda: False,
             get_cuda_device_count=lambda: 0))

    from .trainer_desc import DataFeedDesc

    data_feed_desc = _module(
        base + ".data_feed_desc",
        "fluid.data_feed_desc (ref data_feed_desc.py).",
        dict(DataFeedDesc=DataFeedDesc))

    from .incubate import (MultiSlotDataGenerator,
                           MultiSlotStringDataGenerator)

    data_generator = _module(
        base + ".data_generator",
        "fluid.data_generator (ref incubate/data_generator).",
        dict(MultiSlotDataGenerator=MultiSlotDataGenerator,
             MultiSlotStringDataGenerator=MultiSlotStringDataGenerator))

    def _distribute_lookup_table(*a, **k):
        raise NotImplementedError(
            "distribute_lookup_table is parameter-server plumbing "
            "(SURVEY §4b descope); sparse embeddings shard over the mesh "
            "via VocabParallelEmbedding")

    distribute_lookup_table = _module(
        base + ".distribute_lookup_table",
        "fluid.distribute_lookup_table (PS-era; recorded descope).",
        dict(find_distributed_lookup_table=_distribute_lookup_table))

    class Inferencer:
        """ref inferencer.py (deprecated in the reference itself): thin
        loader+runner over save_inference_model output."""

        def __init__(self, infer_func=None, param_path=None, place=None,
                     parallel=False):
            import warnings

            warnings.warn("fluid.Inferencer is deprecated; use "
                          "paddle_tpu.inference.Predictor", Warning)
            from ..inference.predictor import Predictor

            self._pred = Predictor(param_path)

        def infer(self, inputs, return_numpy=True):
            return self._pred.run(inputs, return_numpy=return_numpy)

    inferencer = _module(
        base + ".inferencer",
        "fluid.inferencer (ref inferencer.py, deprecated).",
        dict(Inferencer=Inferencer))

    def monkey_patch_variable():
        """ref math_op_patch.py: Variables here already carry operator
        methods natively — nothing to patch."""
        return None

    def monkey_patch_varbase():
        return None

    mods = dict(framework=framework, executor=executor, compiler=compiler,
                parallel_executor=parallel_executor, core=core,
                data_feed_desc=data_feed_desc,
                data_generator=data_generator,
                distribute_lookup_table=distribute_lookup_table,
                inferencer=inferencer)
    for k, v in mods.items():
        setattr(fluid_pkg, k, v)
    fluid_pkg.monkey_patch_variable = monkey_patch_variable
    fluid_pkg.monkey_patch_varbase = monkey_patch_varbase
    # ref fluid/__init__.py:72: fleet is re-exported from incubate
    fluid_pkg.fleet = fluid_pkg.incubate.fleet
    return mods
