"""fluid.dygraph compatibility surface (ref: fluid/dygraph/__init__.py).

Eager execution is this framework's default mode, so ``guard`` is a
no-op context; Layer/to_variable map straight onto the native types.
"""
from __future__ import annotations

import contextlib

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer, Sequential, LayerList, ParameterList  # noqa: F401
from ..nn.layers.common import (Linear, Embedding, Dropout)  # noqa: F401
from ..nn.layers.conv import Conv2D  # noqa: F401
from ..nn.layers.norm import BatchNorm2D as BatchNorm  # noqa: F401
from ..framework.io import save_checkpoint, load_checkpoint  # noqa: F401
from ..framework.jit import to_static as jit  # noqa: F401
from ..dist.parallel import DataParallel  # noqa: F401

__all__ = ["guard", "to_variable", "Layer", "Sequential", "LayerList",
           "ParameterList", "Linear", "Embedding", "Dropout", "Conv2D",
           "BatchNorm", "DataParallel", "no_grad", "jit"]


@contextlib.contextmanager
def guard(place=None):
    """Eager mode is the default; kept for source compatibility."""
    yield


def to_variable(value, name=None, zero_copy=None, dtype=None):
    arr = np.asarray(value)
    if dtype is not None:
        arr = arr.astype(dtype)
    return Tensor(arr)


def no_grad(fn=None):
    from ..core import dispatch

    if fn is None:
        return dispatch.no_grad()

    def wrapped(*a, **k):
        with dispatch.no_grad():
            return fn(*a, **k)

    return wrapped
