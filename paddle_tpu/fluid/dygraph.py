"""fluid.dygraph compatibility surface (ref: fluid/dygraph/__init__.py).

Eager execution is this framework's default mode, so ``guard`` is a
no-op context; Layer/to_variable map straight onto the native types.
"""
from __future__ import annotations

import contextlib

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer, Sequential, LayerList, ParameterList  # noqa: F401
from ..nn.layers.common import (Linear, Embedding, Dropout)  # noqa: F401
from ..nn.layers.conv import Conv2D  # noqa: F401
from ..nn.layers.norm import BatchNorm2D as BatchNorm  # noqa: F401
from ..framework.io import save_checkpoint, load_checkpoint  # noqa: F401
from ..framework.jit import to_static as jit  # noqa: F401
from ..dist.parallel import DataParallel  # noqa: F401

__all__ = ["guard", "to_variable", "Layer", "Sequential", "LayerList",
           "ParameterList", "Linear", "Embedding", "Dropout", "Conv2D",
           "BatchNorm", "DataParallel", "no_grad", "jit",
           "Conv2DTranspose", "Conv3D", "Conv3DTranspose", "GroupNorm",
           "LayerNorm", "Pool2D", "PRelu", "SpectralNorm",
           "BilinearTensorProduct", "NCE", "GRUUnit", "TreeConv",
           "NoamDecay", "PiecewiseDecay", "PolynomialDecay", "CosineDecay",
           "ExponentialDecay", "InverseTimeDecay", "NaturalExpDecay",
           "enable_dygraph", "disable_dygraph", "enabled", "grad",
           "save_dygraph", "load_dygraph", "BackwardStrategy",
           "ParallelEnv", "prepare_context", "TracedLayer",
           "dygraph_to_static_func", "dygraph_to_static_code",
           "dygraph_to_static_output", "dygraph_to_static_program",
           "start_gperf_profiler", "stop_gperf_profiler", "Parameter",
           "ProgramTranslator", "declarative"]


@contextlib.contextmanager
def guard(place=None):
    """Eager mode is the default; kept for source compatibility."""
    yield


def to_variable(value, name=None, zero_copy=None, dtype=None):
    arr = np.asarray(value)
    if dtype is not None:
        arr = arr.astype(dtype)
    return Tensor(arr)


def no_grad(fn=None):
    from ..core import dispatch

    if fn is None:
        return dispatch.no_grad()

    def wrapped(*a, **k):
        with dispatch.no_grad():
            return fn(*a, **k)

    return wrapped


# -- fluid.dygraph layer catalogue (ref: fluid/dygraph/nn.py) ---------------
from ..nn.layers.conv import (Conv2DTranspose, Conv3D,  # noqa: F401,E402
                              Conv3DTranspose)
from ..nn.layers.norm import (GroupNorm, LayerNorm)  # noqa: F401,E402
from ..nn.layer import Parameter  # noqa: F401,E402
from .. import ops as _ops  # noqa: E402
from ..nn import functional as _F  # noqa: E402
from ..optim import lr as _lr  # noqa: E402


class Pool2D(Layer):
    """ref: dygraph/nn.py Pool2D — config-object pooling layer."""

    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True, data_format="NCHW"):
        super().__init__()
        if data_format != "NCHW":
            raise NotImplementedError(
                "Pool2D: NCHW only (transpose NHWC inputs first)")
        self._cfg = dict(pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride, pool_padding=pool_padding,
                         global_pooling=global_pooling, ceil_mode=ceil_mode,
                         exclusive=exclusive)

    def forward(self, x):
        from .layers import pool2d

        return pool2d(x, **self._cfg)


class PRelu(Layer):
    """ref: dygraph/nn.py PRelu; mode in {'all', 'channel', 'element'}."""

    def __init__(self, mode="all", channel=None, input_shape=None,
                 param_attr=None, dtype="float32"):
        super().__init__()
        if mode == "all":
            shape = (1,)
        elif mode == "channel":
            shape = (channel,)
        else:
            shape = tuple(input_shape[1:])
        from ..nn import initializer as I

        self.weight = self.create_parameter(
            shape, attr=param_attr, dtype=dtype,
            default_initializer=I.Constant(0.25))
        self.mode = mode

    def forward(self, x):
        w = self.weight
        if self.mode == "channel":
            shp = [1, -1] + [1] * (len(x.shape) - 2)
            w = w.reshape(shp)
        return _ops.maximum(x, x * 0.0) + w * _ops.minimum(x, x * 0.0)


class SpectralNorm(Layer):
    """ref: dygraph/nn.py SpectralNorm: normalizes the input weight by
    its leading singular value (power iteration each call)."""

    def __init__(self, weight_shape=None, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps

    def forward(self, weight):
        from ..ops.norm_ops import spectral_norm

        return spectral_norm(weight, dim=self.dim,
                             power_iters=self.power_iters, eps=self.eps)


class BilinearTensorProduct(Layer):
    """ref: dygraph/nn.py BilinearTensorProduct: out_k = x W_k y + b."""

    def __init__(self, input1_dim, input2_dim, output_dim, name=None,
                 act=None, param_attr=None, bias_attr=None,
                 dtype="float32"):
        super().__init__()
        self.weight = self.create_parameter(
            (output_dim, input1_dim, input2_dim), attr=param_attr,
            dtype=dtype)
        self.bias = self.create_parameter((output_dim,), attr=bias_attr,
                                          dtype=dtype, is_bias=True)
        self.act = act

    def forward(self, x, y):
        from ..ops.misc import bilinear_tensor_product

        out = bilinear_tensor_product(x, y, weight=self.weight,
                                      bias=self.bias)
        if self.act is not None:
            out = getattr(_F, self.act)(out)
        return out


class NCE(Layer):
    """ref: dygraph/nn.py NCE: holds the (V, D) weight/bias and applies
    the NCE loss op."""

    def __init__(self, num_total_classes, dim, sample_weight=None,
                 param_attr=None, bias_attr=None, num_neg_samples=10,
                 sampler="uniform", custom_dist=None, seed=0,
                 is_sparse=False, dtype="float32"):
        super().__init__()
        self.weight = self.create_parameter((num_total_classes, dim),
                                            attr=param_attr, dtype=dtype)
        self.bias = self.create_parameter((num_total_classes,),
                                          attr=bias_attr, dtype=dtype,
                                          is_bias=True)
        self.num_total_classes = num_total_classes
        self.num_neg_samples = num_neg_samples
        self.sampler = sampler

    def forward(self, input, label, sample_weight=None):
        from ..ops.labeling import nce

        return nce(input, label, self.num_total_classes,
                   num_neg_samples=self.num_neg_samples,
                   sampler=self.sampler, weight=self.weight,
                   bias=self.bias)


class GRUUnit(Layer):
    """ref: dygraph/nn.py GRUUnit: single fused GRU step with held
    recurrent weights (size is 3*D, fluid convention)."""

    def __init__(self, size, param_attr=None, bias_attr=None,
                 activation="tanh", gate_activation="sigmoid",
                 origin_mode=False, dtype="float32"):
        super().__init__()
        from .rnn import _FluidGRUCell

        self.cell = _FluidGRUCell(size // 3, param_attr, bias_attr,
                                  gate_activation, activation, origin_mode)
        self.origin_mode = origin_mode
        self.gate_activation = gate_activation
        self.activation = activation

    def forward(self, input, hidden):
        from .rnn import _gru_step

        return _gru_step(self.cell, input, hidden, self.gate_activation,
                         self.activation, self.origin_mode)


class TreeConv(Layer):
    """ref: dygraph/nn.py TreeConv over the TBCNN tree_conv op."""

    def __init__(self, feature_size, output_size, num_filters=1,
                 max_depth=2, act="tanh", param_attr=None, bias_attr=None,
                 name=None, dtype="float32"):
        super().__init__()
        self.weight = self.create_parameter(
            (feature_size, 3, output_size, num_filters), attr=param_attr,
            dtype=dtype)
        self.bias = self.create_parameter((num_filters,), attr=bias_attr,
                                          dtype=dtype, is_bias=True)
        self.max_depth = max_depth
        self.act = act

    def forward(self, nodes_vector, edge_set):
        from ..ops.misc import tree_conv

        out = tree_conv(nodes_vector, edge_set, self.weight.shape[2],
                        self.weight.shape[3], self.max_depth, act=None,
                        weight=self.weight)
        out = out + self.bias.reshape([1, 1, 1, -1])
        if self.act is not None:
            out = getattr(_F, self.act)(out)
        return out


# -- LR decay classes under the dygraph names -------------------------------
NoamDecay = _lr.NoamDecay
PiecewiseDecay = _lr.PiecewiseDecay
PolynomialDecay = _lr.PolynomialDecay
CosineDecay = _lr.CosineAnnealingDecay
ExponentialDecay = _lr.ExponentialDecay
InverseTimeDecay = _lr.InverseTimeDecay
NaturalExpDecay = _lr.NaturalExpDecay


# -- mode switches / misc (ref: fluid/dygraph/base.py) ----------------------


def enable_dygraph(place=None):
    """Eager IS the default mode; provided for source compatibility."""
    import paddle_tpu as _pt

    _pt.disable_static()


def disable_dygraph():
    import paddle_tpu as _pt

    _pt.enable_static()


def enabled():
    from ..static_ import in_static_mode

    return not in_static_mode()


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    import paddle_tpu as _pt

    return _pt.grad(outputs, inputs, grad_outputs=grad_outputs,
                    retain_graph=retain_graph, create_graph=create_graph,
                    allow_unused=allow_unused)


def save_dygraph(state_dict, model_path):
    """ref: dygraph/checkpoint.py save_dygraph -> <path>.pdparams (npz)."""
    import paddle_tpu as _pt

    _pt.save(state_dict, model_path + ".pdparams")


def load_dygraph(model_path, keep_name_table=False):
    """ref: dygraph/checkpoint.py load_dygraph; returns (params, opt)."""
    import os

    import paddle_tpu as _pt

    p = model_path + ".pdparams" if not model_path.endswith(".pdparams") \
        else model_path
    params = _pt.load(p)
    opt_path = model_path + ".pdopt"
    opt = _pt.load(opt_path) if os.path.exists(opt_path) else None
    return params, opt


class BackwardStrategy:
    """ref: imperative BackwardStrategy: sort_sum_gradient toggles
    deterministic gradient accumulation order. XLA accumulation is
    already deterministic; the knob is accepted and recorded."""

    def __init__(self):
        self.sort_sum_gradient = False


class ParallelEnv:
    """ref: dygraph/parallel.py ParallelEnv — rank/world info."""

    def __init__(self):
        from ..dist import env as _denv

        self._rank = _denv.get_rank() if hasattr(_denv, "get_rank") else 0
        self._world = _denv.get_world_size() \
            if hasattr(_denv, "get_world_size") else 1

    @property
    def nranks(self):
        return self._world

    @property
    def local_rank(self):
        return self._rank

    @property
    def dev_id(self):
        return self._rank

    @property
    def current_endpoint(self):
        return "127.0.0.1:0"

    @property
    def trainer_endpoints(self):
        return ["127.0.0.1:0"]


def prepare_context(strategy=None):
    """ref: dygraph/parallel.py prepare_context: collective init. Mesh
    setup happens via dist.init_parallel_env/fleet.init here."""
    from ..dist import env as _denv

    return _denv


class TracedLayer:
    """ref: dygraph/jit.py TracedLayer: trace a Layer once, then run /
    save the traced program (here: a jitted callable +
    save_inference_model)."""

    def __init__(self, fn, example_args):
        self._fn = fn
        self._args = example_args

    @staticmethod
    def trace(layer, inputs):
        import paddle_tpu as _pt

        fn = _pt.jit(layer)
        out = fn(*inputs)
        traced = TracedLayer(fn, inputs)
        return out, traced

    def __call__(self, *args):
        return self._fn(*args)

    def save_inference_model(self, dirname, feed=None, fetch=None):
        if hasattr(self._fn, "save"):
            return self._fn.save(dirname)
        raise NotImplementedError(
            "trace target lacks save(); use paddle_tpu.jit + "
            "save_inference_model")


def dygraph_to_static_func(fn):
    from ..framework.jit import to_static

    return to_static(fn)


dygraph_to_static_code = dygraph_to_static_func
dygraph_to_static_output = dygraph_to_static_func
dygraph_to_static_program = dygraph_to_static_func


def start_gperf_profiler():
    from ..utils.profiler import start_profiler

    return start_profiler()


def stop_gperf_profiler():
    from ..utils.profiler import stop_profiler

    return stop_profiler()

# dygraph -> static conversion surface (ref: dygraph/dygraph_to_static/
# + dygraph/jit.py declarative); home: fluid/dygraph_to_static.py
from .dygraph_to_static import (ProgramTranslator,  # noqa: F401,E402
                                declarative)
