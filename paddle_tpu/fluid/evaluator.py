"""fluid.evaluator (ref: python/paddle/fluid/evaluator.py).

The reference's Evaluator classes are deprecated static-graph state
accumulators (each keeps counter Variables in the scope and reads them
back through the Executor); the streaming metrics in
``paddle_tpu.metrics`` are the living equivalents, so these classes are
thin program-independent fronts over them that keep the
``reset(executor)`` / ``eval(executor)`` calling convention.
"""
from __future__ import annotations

import warnings

import numpy as np

from .. import metrics as _metrics

__all__ = ["Evaluator", "ChunkEvaluator", "EditDistance", "DetectionMAP"]


class Evaluator:
    """Base evaluator (ref: evaluator.py:45). State lives host-side; the
    executor arguments are accepted for source compatibility and unused
    (there are no scope counter variables to zero — XLA programs are
    pure)."""

    def __init__(self, name=None, **kwargs):
        warnings.warn(
            f"fluid.evaluator.{type(self).__name__} is deprecated; use "
            "paddle_tpu.metrics instead. NOTE: executor/program arguments "
            "are accepted for source compatibility but IGNORED — metrics "
            "come only from values passed to update(); accumulator "
            "sub-programs the reference would build are never run",
            Warning)
        self.name = name or type(self).__name__.lower()
        self.states = []
        self.metrics = []

    def reset(self, executor=None, reset_program=None):
        # subclasses here carry a streaming metric; a user subclass of the
        # reference pattern (custom self.states) just gets them zeroed
        m = getattr(self, "_metric", None)
        if m is not None:
            m.reset()
        self.states = []

    def eval(self, executor=None, eval_program=None):
        raise NotImplementedError


class ChunkEvaluator(Evaluator):
    """Chunk-level P/R/F1 accumulator (ref: evaluator.py:127). ``update``
    feeds per-batch tag sequences; ``eval`` returns (precision, recall,
    f1) like the reference's eval()."""

    def __init__(self, input=None, label=None, chunk_scheme="IOB",
                 num_chunk_types=1, excluded_chunk_types=None, **kwargs):
        super().__init__(**kwargs)
        self._metric = _metrics.ChunkEvaluator(
            chunk_scheme=chunk_scheme, num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types)

    def update(self, pred, label, seq_length=None):
        self._metric.update(pred, label, seq_length)

    def eval(self, executor=None, eval_program=None):
        return self._metric.accumulate()


class EditDistance(Evaluator):
    """Average edit distance accumulator (ref: evaluator.py:218)."""

    def __init__(self, input=None, label=None, ignored_tokens=None,
                 **kwargs):
        super().__init__(**kwargs)
        self.ignored_tokens = ignored_tokens
        self._metric = _metrics.EditDistance()

    def update(self, distances, seq_num):
        self._metric.update(np.asarray(distances), int(seq_num))

    def eval(self, executor=None, eval_program=None):
        return self._metric.accumulate()


class DetectionMAP(Evaluator):
    """Detection mAP accumulator (ref: evaluator.py:299)."""

    def __init__(self, input=None, gt_label=None, gt_box=None,
                 gt_difficult=None, class_num=None,
                 background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral", **kwargs):
        super().__init__(**kwargs)
        self._metric = _metrics.DetectionMAP(
            overlap_threshold=overlap_threshold, map_type=ap_version,
            evaluate_difficult=evaluate_difficult, class_num=class_num)

    def update(self, detections, gts):
        self._metric.update(detections, gts)

    def get_map_var(self):
        return None  # no scope variable: the accumulator is host-side

    def eval(self, executor=None, eval_program=None):
        return self._metric.accumulate()
