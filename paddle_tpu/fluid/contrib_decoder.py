"""fluid.contrib.decoder beam-search decoder API
(ref: python/paddle/fluid/contrib/decoder/beam_search_decoder.py —
InitState/StateCell/TrainingDecoder/BeamSearchDecoder, the book ch.8
machine-translation decoder stack).

Design note (same convention as fluid/rnn.py StaticRNN): the reference
builds per-step graphs inside ``with decoder.block():`` under a
DynamicRNN/While op. Python context managers cannot re-run their body,
and the XLA-era executor replays per-step functions instead of
sub-block descs — so the step body here is a CALLABLE registered with
``decoder.block(fn)`` (also usable as a decorator). Everything else —
the StateCell updater protocol, expansion of states over beams, the
log-prob accumulation + top-k beam step, end-id freezing — follows the
reference op for op.
"""
from __future__ import annotations

import numpy as np

from .. import ops as _ops
from ..core.tensor import Tensor

__all__ = ["InitState", "StateCell", "TrainingDecoder",
           "BeamSearchDecoder"]


class InitState:
    """ref: beam_search_decoder.py:43 — initial decoder state, either a
    concrete tensor (``init``) or a filled shape."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                "init_boot must be provided to infer the init batch size")
        else:
            B = init_boot.shape[0]
            self._init = _ops.full([B] + list(shape or []), value,
                                   dtype=dtype)
        self._need_reorder = need_reorder

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class StateCell:
    """ref: beam_search_decoder.py:159 — named states + inputs with a
    user updater::

        cell = StateCell(inputs={'x': None}, states={'h': InitState(...)},
                         out_state='h')

        @cell.state_updater
        def updater(cell):
            h = some_layers(cell.get_input('x'), cell.get_state('h'))
            cell.set_state('h', h)
    """

    def __init__(self, inputs, states, out_state, name=None):
        self._input_names = list(inputs)
        self._init_states = dict(states)
        self._state_names = list(states)
        self._out_state = out_state
        self._updater = None
        self._cur_states = {}
        self._new_states = {}
        self._cur_inputs = {}

    def state_updater(self, updater):
        self._updater = updater
        return updater

    def _reset(self):
        self._cur_states = {k: v.value
                            for k, v in self._init_states.items()}

    def get_input(self, input_name):
        if input_name not in self._cur_inputs:
            raise ValueError(f"input {input_name} not fed this step")
        return self._cur_inputs[input_name]

    def get_state(self, state_name):
        if state_name not in self._cur_states:
            raise ValueError(f"unknown state {state_name}")
        return self._cur_states[state_name]

    def set_state(self, state_name, state_value):
        # reference semantics: the new value is visible to get_state
        # immediately after compute_state (the book pattern reads
        # get_state('h') BETWEEN compute_state and update_states)
        self._cur_states[state_name] = state_value
        self._new_states[state_name] = state_value

    def compute_state(self, inputs):
        if self._updater is None:
            raise ValueError("register a @state_cell.state_updater first")
        unknown = set(inputs) - set(self._input_names)
        if unknown:
            raise ValueError(f"inputs {sorted(unknown)} not declared on "
                             "this StateCell")
        self._cur_inputs = dict(inputs)
        self._new_states = {}
        self._updater(self)

    def update_states(self):
        """Commit point for the recurrence (ref: writes the RNN memory;
        states here already live in _cur_states, so this closes the
        step)."""
        self._new_states = {}

    def out_state(self):
        return self._cur_states[self._out_state]


class TrainingDecoder:
    """ref: beam_search_decoder.py:384 — teacher-forced decoding over a
    target sequence. Step body is a callable (see module note)::

        decoder = TrainingDecoder(cell)

        @decoder.block
        def _(d):
            w = d.step_input(trg_emb)          # (B, D) at the current step
            d.state_cell.compute_state(inputs={'x': w})
            score = project(d.state_cell.get_state('h'))
            d.state_cell.update_states()
            d.output(score)

        outputs = decoder()                    # (B, T, vocab)
    """

    def __init__(self, state_cell, name=None):
        self._state_cell = state_cell
        self._fn = None
        self._step_inputs = []
        self._static_inputs = []
        self._step_outputs = None
        self._t = 0

    @property
    def state_cell(self):
        return self._state_cell

    def block(self, fn=None):
        if fn is None:
            raise TypeError(
                "with decoder.block(): is the reference spelling; here "
                "the step body is a callable — use @decoder.block or "
                "decoder.block(fn) (same convention as StaticRNN.step)")
        self._fn = fn
        return fn

    def step_input(self, x):
        """Register a (B, T, ...) sequence; returns the slice for the
        step being executed. Identity check, not ``in``: Tensor __eq__
        is elementwise."""
        if not any(x is s for s in self._step_inputs):
            self._step_inputs.append(x)
        return x[:, self._t]

    def static_input(self, x):
        """A non-stepped input visible in every step."""
        if not any(x is s for s in self._static_inputs):
            self._static_inputs.append(x)
        return x

    def output(self, *outputs):
        self._step_outputs = outputs if len(outputs) > 1 else outputs[0]

    def __call__(self):
        if self._fn is None:
            raise ValueError("register the step body with decoder.block")
        # discover T by running the body once (step 0 registers inputs)
        self._state_cell._reset()
        self._t = 0
        self._fn(self)
        T = self._step_inputs[0].shape[1] if self._step_inputs else 1
        outs = [self._step_outputs]
        for t in range(1, T):
            self._t = t
            self._fn(self)
            outs.append(self._step_outputs)
        return _ops.stack(outs, axis=1)


class BeamSearchDecoder:
    """ref: beam_search_decoder.py:523 — beam decode driven by the same
    StateCell the TrainingDecoder trained. Owns the target-ids embedding
    and the vocab projection, as the reference decode() does; decode()
    marks the (built-in) decode graph, ``decoder()`` runs it and returns
    ``(translation_ids, translation_scores)`` — ids padded with
    ``end_id`` as ``(B, beam_size, max_len)``, scores ``(B, beam_size)``
    (the XLA-era dense replacement for the reference's LoD beams)."""

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict=None, topk_size=50,
                 sparse_emb=True, max_len=100, beam_size=1, end_id=1,
                 name=None):
        from ..nn.layers.common import Embedding, Linear

        self._state_cell = state_cell
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = int(target_dict_dim)
        self._word_dim = int(word_dim)
        self._input_var_dict = dict(input_var_dict or {})
        self._topk_size = int(topk_size)
        self._max_len = int(max_len)
        self._beam_size = int(beam_size)
        self._end_id = int(end_id)
        self._emb = Embedding(self._target_dict_dim, self._word_dim)
        state_dim = int(np.prod(
            state_cell._init_states[state_cell._out_state].value.shape[1:]))
        self._fc = Linear(state_dim, self._target_dict_dim)
        self._decoded = False

    @property
    def state_cell(self):
        return self._state_cell

    def decode(self):
        """The default decode graph is built in; subclass and override
        to customize (ref contract)."""
        self._decoded = True

    def __call__(self):
        import jax.numpy as jnp

        if not self._decoded:
            self.decode()
        cell = self._state_cell
        cell._reset()
        K, V, E = self._beam_size, self._target_dict_dim, self._end_id

        ids0 = _ops.reshape(self._init_ids, [-1])
        B = ids0.shape[0]
        # expand batch -> batch*beam (ref: sequence_expand over beams)
        ids = _ops.reshape(
            _ops.tile(_ops.reshape(ids0, [B, 1]), [1, K]), [B * K])
        scores = np.full((B, K), -1e9, np.float32)
        scores[:, 0] = 0.0  # only beam 0 live initially (identical beams)
        scores = Tensor(jnp.asarray(scores.reshape(B * K)), _internal=True)
        for name in cell._state_names:
            st = cell.get_state(name)
            cell._cur_states[name] = _ops.reshape(
                _ops.tile(_ops.reshape(st, [B, 1] + list(st.shape[1:])),
                          [1, K] + [1] * (len(st.shape) - 1)),
                [B * K] + list(st.shape[1:]))
        static_feeds = {}
        for iname, ivar in self._input_var_dict.items():
            if iname not in cell._input_names:
                raise ValueError(
                    f"Variable {iname} not found in StateCell!")
            static_feeds[iname] = _ops.reshape(
                _ops.tile(_ops.reshape(ivar, [B, 1] + list(ivar.shape[1:])),
                          [1, K] + [1] * (len(ivar.shape) - 1)),
                [B * K] + list(ivar.shape[1:]))

        finished = Tensor(jnp.zeros((B * K,), bool), _internal=True)
        out_ids = []
        for _t in range(self._max_len):
            emb = self._emb(ids)
            feeds = dict(static_feeds)
            for iname in cell._input_names:
                if iname not in feeds:
                    feeds[iname] = emb
            cell.compute_state(inputs=feeds)
            cell.update_states()
            logits = self._fc(cell.out_state())
            logp = _ops.log_softmax(logits, axis=-1)
            # finished beams: only end_id continues, at zero added cost
            mask = np.full((1, V), -np.inf, np.float32)
            mask[0, E] = 0.0
            logp = _ops.where(_ops.reshape(finished, [-1, 1]),
                              Tensor(jnp.asarray(mask), _internal=True)
                              + _ops.zeros_like(logp), logp)
            total = _ops.reshape(scores, [-1, 1]) + logp       # (B*K, V)
            flat = _ops.reshape(total, [B, K * V])
            top_scores, top_idx = _ops.topk(flat, k=K)         # (B, K)
            parent = top_idx // V                              # beam index
            word = top_idx % V                                 # token
            gather_base = (_ops.arange(0, B, dtype="int64") * K)
            src = _ops.reshape(
                _ops.reshape(gather_base, [B, 1]) + parent, [B * K])
            # reorder beam-major state by parent beam
            for name in cell._state_names:
                cell._cur_states[name] = _ops.index_select(
                    cell._cur_states[name], src, axis=0)
            for prev in range(len(out_ids)):
                out_ids[prev] = _ops.index_select(out_ids[prev], src,
                                                  axis=0)
            finished = _ops.index_select(finished, src, axis=0)
            ids = _ops.reshape(word, [B * K])
            scores = _ops.reshape(top_scores, [B * K])
            out_ids.append(ids)
            finished = _ops.logical_or(finished,
                                       _ops.equal(ids, _ops.full_like(
                                           ids, E)))
            if bool(np.all(np.asarray(finished.numpy()))):
                break

        seq = _ops.stack(out_ids, axis=1)                      # (B*K, L)
        translation_ids = _ops.reshape(seq, [B, K, seq.shape[1]])
        translation_scores = _ops.reshape(scores, [B, K])
        return translation_ids, translation_scores
