"""fluid.wrapped_decorator (ref: python/paddle/fluid/wrapped_decorator.py).

``wrap_decorator`` turns a function-transforming decorator into a
signature-preserving one (the reference uses the ``decorator`` package
for the same purpose); ``signature_safe_contextmanager`` is the
signature-preserving contextlib.contextmanager both codebases use on
public guard APIs so help()/inspect show the real argument list.
"""
from __future__ import annotations

import contextlib

import decorator

__all__ = ["wrap_decorator", "signature_safe_contextmanager"]


def wrap_decorator(decorator_func):
    @decorator.decorator
    def __impl__(func, *args, **kwargs):
        wrapped_func = decorator_func(func)
        return wrapped_func(*args, **kwargs)

    return __impl__


signature_safe_contextmanager = wrap_decorator(contextlib.contextmanager)
