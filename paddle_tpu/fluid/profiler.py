"""fluid.profiler submodule (ref: python/paddle/fluid/profiler.py).

The reference drives the C++ platform profiler (nvprof ranges, per-op
timers); here every name forwards to ``paddle_tpu.utils.profiler``,
whose backend is ``jax.profiler`` trace collection (XPlane traces for
xprof/tensorboard — the TPU-native equivalent of the op timeline).
"""
from ..utils.profiler import (profiler, start_profiler,  # noqa: F401
                              stop_profiler, reset_profiler, cuda_profiler,
                              add_profiler_step, StepTimer)

__all__ = ["profiler", "start_profiler", "stop_profiler", "reset_profiler",
           "cuda_profiler", "add_profiler_step", "StepTimer"]
