"""fluid.profiler submodule (ref: python/paddle/fluid/profiler.py).

The reference drives the C++ platform profiler (nvprof ranges, per-op
timers); here every name forwards to ``paddle_tpu.utils.profiler``, whose
backend is ``jax.profiler`` trace collection (XPlane traces for
xprof/tensorboard — the TPU-native equivalent of the op timeline) plus
the ``paddle_tpu.obs`` span tracer: a reference-style

    with fluid.profiler.profiler('All', 'total'):
        ...train loop...

block now records real host-side spans (executor compiles/runs,
dataloader waits) into the obs ring buffer — export them with
``paddle_tpu.obs.export_chrome_trace(path)`` — instead of being a no-op.
``span(name, **attrs)`` is the nvprof-range analog for custom blocks.
"""
from ..utils.profiler import (profiler, start_profiler,  # noqa: F401
                              stop_profiler, reset_profiler, cuda_profiler,
                              add_profiler_step, StepTimer, span)

__all__ = ["profiler", "start_profiler", "stop_profiler", "reset_profiler",
           "cuda_profiler", "add_profiler_step", "StepTimer", "span"]
