"""fluid.distributed (ref: python/paddle/fluid/distributed/) — the
downpour/pslib parameter-server client package. PS mode is a recorded
descope (SURVEY §4b); the Fleet here keeps worker-side lifecycle
working over the collective design and raises the descope error on
pserver-side entry points.
"""
from .fleet import Fleet  # noqa: F401

__all__ = ["Fleet"]
