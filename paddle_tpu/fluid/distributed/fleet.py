"""ref: python/paddle/fluid/distributed/fleet.py — downpour Fleet."""
from __future__ import annotations

__all__ = ["Fleet"]

_DESCOPE = ("parameter-server mode is descoped on TPU (SURVEY §4b): "
            "sparse tables shard over the mesh via "
            "VocabParallelEmbedding; use dist.fleet / fleet.init")


class Fleet:
    """ref: distributed/fleet.py:20. Worker-side lifecycle is live
    (rank/size from the jax distributed env); pserver-side methods
    raise the recorded descope."""

    def __init__(self):
        self._opt_info = None

    def stop(self):
        from ...dist import env as denv

        if denv.get_world_size() > 1:
            from ...dist.collective import barrier

            barrier()

    def init_worker(self, opt_info=None):
        self._opt_info = opt_info

    def worker_num(self):
        from ...dist import env as denv

        return denv.get_world_size()

    def worker_index(self):
        from ...dist import env as denv

        return denv.get_rank()

    def init_pserver(self, opt_info=None):
        raise NotImplementedError(_DESCOPE)

    def init_pserver_model(self):
        raise NotImplementedError(_DESCOPE)

    def save_pserver_model(self, save_path):
        raise NotImplementedError(_DESCOPE)
