"""fluid.layers compatibility surface.

Ref: python/paddle/fluid/layers/* __all__ — the symbol set fluid-era
user code imports. Every name here resolves to the TPU-native
implementation; renamed ops get thin aliases (reduce_sum -> ops.sum,
fc -> Linear-on-the-fly, While/Switch -> lax-backed control flow).
Parameter-creating functions follow the fluid convention of creating
fresh parameters per call — call them while building a model/program.
"""
from __future__ import annotations

import numpy as np

from .. import ops as _ops
from ..core.tensor import Tensor
from ..nn import functional as _F
from ..nn.layers.common import Linear, Embedding
from ..nn.param_attr import ParamAttr
from ..static_ import data as _static_data
from ..optim import lr as _lr

# -- wholesale re-exports: everything the functional namespaces already
# provide under the fluid name ----------------------------------------------
_g = globals()
for _src in (_ops, _F):
    for _n in dir(_src):
        if not _n.startswith("_") and _n not in _g:
            _g[_n] = getattr(_src, _n)

# decode / beam API lives in inference
from ..inference.decoder import (dynamic_decode, BeamSearchDecoder,  # noqa: F401,E402
                                 Decoder, beam_search, greedy_search)
from ..metrics import Auc  # noqa: F401,E402


def data(name, shape, append_batch_size=True, dtype="float32",
         lod_level=0, type=None, stop_gradient=True):
    """Legacy fluid.layers.data (ref: layers/io.py:48): unlike 2.x
    ``static.data``, the declared ``shape`` is PER-SAMPLE and a batch
    dimension is prepended by default — unless any dim is already
    -1/None, which the reference treats as the user declaring the full
    shape. The batch dim records as 1 (the placeholder for -1 here);
    the Executor re-traces per fed batch size, so any batch works at
    run time. A string in the third position is the 2.x positional
    dtype (``data(name, full_shape, "float32")``) and implies the full
    shape was given."""
    if isinstance(append_batch_size, str):
        dtype, append_batch_size = append_batch_size, False
    import builtins  # `any` is shadowed by the ops re-export above

    if builtins.any(s in (-1, None) for s in shape):
        append_batch_size = False  # ref: a variable dim means full shape
    if append_batch_size:
        shape = [-1] + list(shape)
    return _static_data(name, shape, dtype=dtype, lod_level=lod_level)


def tanh_shrink(x, name=None):
    """Fluid-era spelling (ref: layers/ops.py __activations_noattr__)."""
    return _ops.activation.tanhshrink(x)


def hard_shrink(x, threshold=None):
    """Fluid-era spelling (ref: layers/ops.py:104; op default 0.5)."""
    return _F.hardshrink(x, 0.5 if threshold is None else threshold)


def accuracy(input, label, k=1, correct=None, total=None):
    """Graph-compatible top-k batch accuracy (ref: the accuracy op in
    layers/metric_op.py:31): built from ops, so it records into a static
    Program (the book-example `acc = layers.accuracy(prob, label)`
    fetched per batch) and also runs eagerly. The host-side numpy
    variant with fluid top_k tie semantics stays at
    ``paddle_tpu.metrics.accuracy``."""
    from .. import ops as _ops

    if correct is not None or total is not None:
        import warnings

        warnings.warn(
            "layers.accuracy(correct=, total=): the running-counter "
            "outputs are ignored here (stream with metrics.Accuracy "
            "instead); only the batch accuracy is returned",
            RuntimeWarning)
    _, topi = _ops.topk(input, k, axis=-1)
    lab = _ops.reshape(label, [-1, 1]).astype("int64")
    hit = _ops.cast(_ops.equal(topi.astype("int64"), lab), "float32")
    # top-k indices are distinct, so each row hits at most once
    return _ops.mean(_ops.sum(hit, axis=-1))
from ..ops.control_flow import (cond, while_loop, case,  # noqa: F401,E402
                                switch_case)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Streaming-free AUC of one batch (ref: metric_op.py auc)."""
    m = Auc(curve=curve, num_thresholds=num_thresholds)
    m.update(input, label)
    return m.accumulate()


# -- renamed reductions / elementwise ---------------------------------------
reduce_sum = _ops.sum
reduce_mean = _ops.mean
reduce_max = _ops.max
reduce_min = _ops.min
reduce_prod = _ops.prod
reduce_all = _ops.all
reduce_any = _ops.any
elementwise_add = _ops.add
elementwise_sub = _ops.subtract
elementwise_mul = _ops.multiply
elementwise_div = _ops.divide
elementwise_max = _ops.maximum
elementwise_min = _ops.minimum
elementwise_mod = _ops.remainder
elementwise_floordiv = _ops.floor_divide
elementwise_pow = _ops.pow
hard_sigmoid = _F.hardsigmoid
hard_swish = _F.hardswish
image_resize_short = None  # defined below
smooth_l1 = _F.smooth_l1_loss
kldiv_loss = _F.kl_div
sigmoid_cross_entropy_with_logits = _F.binary_cross_entropy_with_logits
warpctc = _F.ctc_loss
resize_bilinear = _ops.resize_bilinear
resize_nearest = _ops.resize_nearest
grid_sampler = _ops.grid_sample
uniform_random = _ops.uniform
gaussian_random = _ops.randn


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the short side equals out_short_len (ref: nn.py
    image_resize_short)."""
    import builtins

    # NB: builtins.* — the module namespace re-exports ops.min/ops.round
    h, w = input.shape[2], input.shape[3]
    short = builtins.min(h, w)
    oh = int(builtins.round(h * out_short_len / short))
    ow = int(builtins.round(w * out_short_len / short))
    return _ops.image_resize(input, out_shape=[oh, ow], resample=resample)


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """Fully-connected with fresh parameters (ref: nn.py fc). Flattens
    trailing dims past ``num_flatten_dims`` like the reference."""
    shp = input.shape
    in_dim = int(np.prod(shp[num_flatten_dims:]))
    if len(shp) == num_flatten_dims + 1:
        x = input  # already flat; skip the no-op reshape
    else:
        # -1 for the batch dim: the Executor re-traces per fed batch
        # size, so the flatten must not bake the build-time batch
        x = _ops.reshape(input, [-1] + list(shp[1:num_flatten_dims])
                         + [in_dim])
    lin = Linear(in_dim, size, weight_attr=param_attr,
                 bias_attr=bias_attr)
    out = lin(x)
    if act is not None:
        out = getattr(_F, act)(out)
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """Embedding lookup with fresh table (ref: input.py embedding)."""
    emb = Embedding(size[0], size[1], padding_idx=padding_idx,
                    weight_attr=param_attr)
    return emb(input)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Standalone parameter (ref: tensor.py create_parameter)."""
    from ..nn.layer import Layer

    holder = Layer()
    return holder.create_parameter(shape, attr=attr, dtype=dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    return _ops.full(shape, value, dtype=dtype)


def create_tensor(dtype, name=None, persistable=False):
    return _ops.zeros([1], dtype=dtype)


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return _ops.full(shape, value, dtype=dtype)


def uniform_random_batch_size_like(input, shape, dtype="float32", min=-1.0,
                                   max=1.0, input_dim_idx=0,
                                   output_dim_idx=0, seed=0):
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return _ops.uniform(shape, dtype=dtype, min=min, max=max)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return _ops.randn(shape, dtype=dtype) * std + mean


def pad_constant_like(x, y, pad_value=0.0):
    """Pad y up to x's shape (ref: nn.py pad_constant_like)."""
    pads = []
    for xi, yi in zip(x.shape, y.shape):
        pads += [0, int(xi) - int(yi)]
    return _ops.pad(y, pads, value=pad_value)


def shape(input):
    return _ops.to_tensor(np.asarray(list(input.shape), np.int32))


def rank(input):
    return _ops.to_tensor(np.asarray(len(input.shape), np.int32))


def size(input):
    return _ops.to_tensor(np.asarray(int(np.prod(input.shape)), np.int64))


def range(start, end, step, dtype):  # noqa: A001 (fluid name)
    return _ops.arange(start, end, step, dtype=dtype)


def has_nan(x):
    return _ops.any(_ops.isnan(x))


def has_inf(x):
    return _ops.any(_ops.isinf(x))


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Host-side step counter (the reference keeps it in the scope)."""
    import itertools

    key = counter_name or "@STEP_COUNTER@"
    c = _counters.setdefault(key, itertools.count(begin, step))
    return _ops.to_tensor(np.asarray(next(c), np.int64))


_counters: dict = {}


# -- LR schedules under their fluid names (callable objects) ----------------
def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    return _lr.NoamDecay(d_model, warmup_steps, learning_rate)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    # fluid: lr * rate^(t / decay_steps)  ==  lr * (rate^(1/steps))^t
    return _lr.ExponentialDecay(learning_rate,
                                decay_rate ** (1.0 / decay_steps))


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    # fluid: lr * exp(-rate * t / decay_steps)
    return _lr.NaturalExpDecay(learning_rate, decay_rate / decay_steps)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    # fluid: lr / (1 + rate * t / decay_steps)
    return _lr.InverseTimeDecay(learning_rate, decay_rate / decay_steps)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False):
    return _lr.PolynomialDecay(learning_rate, decay_steps,
                               end_learning_rate, power, cycle)


def piecewise_decay(boundaries, values):
    return _lr.PiecewiseDecay(boundaries, values)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    return _lr.CosineAnnealingDecay(learning_rate,
                                    step_each_epoch * epochs)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    return _lr.LinearWarmup(learning_rate, warmup_steps, start_lr, end_lr)


# -- control flow under fluid names -----------------------------------------
While = while_loop
Switch = switch_case
IfElse = cond


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both"):
    """Debug print passthrough (ref: control_flow.py Print)."""
    import jax

    label = message or "Print"
    jax.debug.print(label + ": {x}", x=input._data
                    if hasattr(input, "_data") else input)
    return input


# -- fluid-era RNN / decode compat (rnn.py) ---------------------------------
from .rnn import (RNNCell, StaticRNN, DynamicRNN, dynamic_lstm,  # noqa: F401,E402
                  dynamic_lstmp, dynamic_gru, gru_unit, lstm_unit, lstm,
                  DecodeHelper, TrainingHelper, GreedyEmbeddingHelper,
                  SampleEmbeddingHelper, BasicDecoder, beam_search_decode)
from ..nn.layers.rnn import (LSTMCell, GRUCell, SimpleRNNCell,  # noqa: F401,E402
                             rnn, birnn, RNN, BiRNN)

# -- distributions under the fluid.layers namespace -------------------------
from ..distribution import (Uniform, Normal, Categorical,  # noqa: F401,E402
                            MultivariateNormalDiag)


# -- LoDTensorArray compat ---------------------------------------------------
# The reference's TensorArray ops power while-loop bodies; eager python
# lists are the direct equivalent (inside ``lax.scan`` the stacked-array
# convention replaces them — SURVEY §3).


def create_array(dtype="float32", initialized_list=None):
    return list(initialized_list or [])


def array_write(x, i, array=None):
    array = [] if array is None else array
    idx = int(i.item() if hasattr(i, "item") else i)
    if idx == len(array):
        array.append(x)
    else:
        array[idx] = x
    return array


def array_read(array, i):
    return array[int(i.item() if hasattr(i, "item") else i)]


def array_length(array):
    return _ops.to_tensor(np.asarray(len(array), np.int64))


def tensor_array_to_tensor(input, axis=1, name=None, use_stack=False):
    out = _ops.stack(input, axis=axis) if use_stack else \
        _ops.concat(input, axis=axis)
    sizes = _ops.to_tensor(np.asarray(
        [t.shape[axis] if not use_stack else 1 for t in input], np.int32))
    return out, sizes


def lod_reset(x, y=None, target_lod=None):
    """Re-associate sequence boundaries (ref: sequence_lod.py lod_reset).
    LoD is explicit in this framework (dense + lengths everywhere), so
    the data passes through and the new per-row lengths are returned
    alongside: (x, lengths)."""
    if target_lod is not None:
        off = np.asarray(target_lod)
        lengths = np.diff(off) if off.ndim == 1 else off
        return x, _ops.to_tensor(lengths.astype(np.int64))
    return x, y


def lod_append(x, level):
    """Single-level LoD only (SURVEY §4b descope): appending deeper
    levels is unsupported; boundaries stay explicit at call sites."""
    raise NotImplementedError(
        "multi-level LoD is descoped; track lengths explicitly")


# -- pooling / padding / crop compat ----------------------------------------


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    if pool_type == "max":
        return _ops.adaptive_max_pool2d(input, pool_size,
                                        return_mask=require_index)
    return _ops.adaptive_avg_pool2d(input, pool_size)


adaptive_pool3d = _ops.adaptive_pool3d


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True, data_format="NCHW"):
    """fluid signature shim over ops.pool2d (which owns the
    exclusive -> count_include_pad semantics); use_cudnn/name are
    legacy no-ops and global pooling derives the window here."""
    if global_pooling or pool_size == -1:
        return _ops.pool2d(input, tuple(input.shape[2:]),
                           pool_type=pool_type, global_pooling=True,
                           exclusive=exclusive)
    return _ops.pool2d(input, pool_size, pool_type=pool_type,
                       pool_stride=pool_stride, pool_padding=pool_padding,
                       ceil_mode=ceil_mode, exclusive=exclusive)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True, data_format="NCDHW"):
    if global_pooling:
        pool_size = tuple(input.shape[2:])
        pool_stride, pool_padding = 1, 0
    if pool_type == "max":
        return _ops.max_pool3d(input, pool_size, stride=pool_stride,
                               padding=pool_padding, ceil_mode=ceil_mode)
    return _ops.avg_pool3d(input, pool_size, stride=pool_stride,
                           padding=pool_padding, ceil_mode=ceil_mode,
                           exclusive=exclusive)


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    """Spatial padding of NCHW maps (ref: nn.py pad2d)."""
    t, b, l, r = [int(p) for p in paddings]
    import jax.numpy as _jnp

    x = input._data if hasattr(input, "_data") else input
    cfg = ((0, 0), (0, 0), (t, b), (l, r))
    jmode = {"constant": "constant", "reflect": "reflect",
             "edge": "edge"}[mode]
    if jmode == "constant":
        out = _jnp.pad(x, cfg, constant_values=pad_value)
    else:
        out = _jnp.pad(x, cfg, mode=jmode)
    return Tensor(out, _internal=True)


def crop(x, shape=None, offsets=None, name=None):
    return _ops.crop_tensor(x, shape=shape, offsets=offsets)


def random_crop(x, shape, seed=None):
    """Random spatial crop (ref: nn.py random_crop): same random offset
    per call, host-drawn from the framework RNG."""
    from ..core import random as _prandom
    import jax as _jax

    full = x.shape
    ndim = len(full)
    sh = list(shape)
    lead = ndim - len(sh)
    key = _prandom.next_key()
    offs = []
    for i, s in enumerate(sh):
        # NB: builtins.max — the module namespace re-exports ops.max
        limit = int(full[lead + i]) - int(s)
        if limit < 0:
            limit = 0
        key, sub = _jax.random.split(key)
        off = int(_jax.random.randint(sub, (), 0, limit + 1))
        offs.append(off)
    import builtins

    sl = builtins.slice  # ops.slice shadows the builtin at module level
    idx = tuple([sl(None)] * lead +
                [sl(o, o + int(s)) for o, s in zip(offs, sh)])
    return x[idx]


def inplace_abn(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
                param_attr=None, bias_attr=None, data_layout="NCHW",
                name=None, moving_mean_name=None, moving_variance_name=None,
                do_model_average_for_mean_and_var=False, use_global_stats=
                False, act_alpha=1.0):
    """Activated batch norm (ref: nn.py inplace_abn). XLA has no in-place
    buffers — this is batch_norm + activation, which XLA fuses anyway.
    Batch statistics are always used: this follows the module's
    fresh-parameters-per-call convention (see ``fc``), so there are no
    trained running stats to normalize with in eval mode."""
    from ..nn.layers.norm import BatchNorm2D

    bn = BatchNorm2D(input.shape[1], momentum=momentum, epsilon=epsilon)
    out = bn(input)
    if act == "leaky_relu":
        return _F.leaky_relu(out, act_alpha)
    if act is not None:
        return getattr(_F, act)(out)
    return out


# -- remaining fluid.layers long tail ---------------------------------------
from ..nn.nets import multi_box_head  # noqa: F401,E402


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=
            None):
    """Host-side python op (ref: nn.py py_func). TPU-native: routes
    through ``jax.pure_callback`` so the call stays jit-compatible; the
    callback runs on host per execution. ``out`` supplies the result
    shape/dtype template (a Tensor or list of Tensors)."""
    import jax

    xs = x if isinstance(x, (list, tuple)) else [x]
    arrays = [v._data if hasattr(v, "_data") else v for v in xs]
    outs = out if isinstance(out, (list, tuple)) else [out]
    templates = [jax.ShapeDtypeStruct(tuple(o.shape), o._data.dtype
                                      if hasattr(o, "_data") else o.dtype)
                 for o in outs]

    def host_fn(*args):
        res = func(*args)
        res = res if isinstance(res, (list, tuple)) else [res]
        return [np.asarray(r._data if hasattr(r, "_data") else r)
                for r in res]

    result = jax.pure_callback(
        host_fn, templates if len(templates) > 1 else templates[0],
        *arrays)
    if isinstance(result, (list, tuple)):
        return [Tensor(r, _internal=True) for r in result]
    return Tensor(result, _internal=True)


def load(out, file_path, load_as_fp16=False):
    """Load a tensor saved by ``save`` (ref: io.py load op): npy/npz."""
    arr = np.load(file_path, allow_pickle=False)
    if hasattr(arr, "files"):
        arr = arr[arr.files[0]]
    if load_as_fp16:
        arr = arr.astype(np.float16)
    t = _ops.to_tensor(arr)
    if out is not None and hasattr(out, "set_value"):
        out.set_value(t)
        return out
    return t


def read_file(reader):
    """Pull one batch from a reader (ref: io.py read_file): with the
    DataLoader pipeline (SURVEY §4b) a reader is any iterator."""
    return next(iter(reader))


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    raise NotImplementedError(
        "py_reader/double_buffer are replaced by paddle_tpu.io.DataLoader "
        "with the native prefetch ring (SURVEY §4b descope)")


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    raise NotImplementedError(
        "py_reader/double_buffer are replaced by paddle_tpu.io.DataLoader "
        "with the native prefetch ring (SURVEY §4b descope)")


def double_buffer(reader, place=None, name=None):
    """Device prefetch overlap is owned by the DataLoader's native ring
    buffer (runtime/cc); pass the reader through."""
    return reader


def reorder_lod_tensor_by_rank(x, rank_table):
    """Reorder batch rows by a rank order (ref: control_flow.py
    reorder_lod_tensor_by_rank). ``rank_table``: (B,) int order, e.g.
    argsort of lengths descending."""
    idx = rank_table.astype("int64") if hasattr(rank_table, "astype") \
        else _ops.to_tensor(np.asarray(rank_table, np.int64))
    return _ops.index_select(x, idx, axis=0)


def merge_selected_rows(x, name=None):
    """Sum duplicate rows of a (rows, values) sparse-gradient pair (ref:
    merge_selected_rows_op). Dense-gradient design: accepts either a
    (rows, values) tuple — merged host-side — or a dense tensor, which
    passes through (XLA grads are already dense)."""
    if isinstance(x, tuple) and len(x) == 2:
        rows, values = x
        r = np.asarray(rows.numpy() if hasattr(rows, "numpy") else rows)
        v = np.asarray(values.numpy() if hasattr(values, "numpy")
                       else values)
        uniq, inv = np.unique(r, return_inverse=True)
        merged = np.zeros((len(uniq),) + v.shape[1:], v.dtype)
        np.add.at(merged, inv, v)
        return _ops.to_tensor(uniq), _ops.to_tensor(merged)
    return x


def get_tensor_from_selected_rows(x, name=None):
    """SelectedRows -> dense tensor (ref: get_tensor_from_selected_rows_op):
    returns the values half of a (rows, values) pair, or the tensor
    itself under the dense-grad design."""
    if isinstance(x, tuple) and len(x) == 2:
        return x[1]
    return x


def continuous_value_model(input, cvm, use_cvm=True):
    """CTR continuous-value feature op (ref: nn.py continuous_value_model):
    keeps the leading (show, click) pair when ``use_cvm`` else drops it."""
    if use_cvm:
        return input
    return input[:, 2:]


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0):
    """Filter instances whose tag set intersects filter_tag (ref:
    filter_by_instag_op, CTR). Host-side (dynamic output): returns
    (filtered, index_map (M, 1), loss_weight (M,))."""
    tags = np.asarray(ins_tag.numpy() if hasattr(ins_tag, "numpy")
                      else ins_tag).reshape(-1)
    want = set(np.asarray(filter_tag.numpy() if hasattr(filter_tag, "numpy")
                          else filter_tag).reshape(-1).tolist())
    keep = np.asarray([int(t) in want for t in tags], bool)
    idx = np.nonzero(keep)[0]
    data = np.asarray(ins.numpy() if hasattr(ins, "numpy") else ins)
    if len(idx) == 0:
        out = np.full((1,) + data.shape[1:], out_val_if_empty, data.dtype)
        return (_ops.to_tensor(out),
                _ops.to_tensor(np.zeros((1, 1), np.int64)),
                _ops.to_tensor(np.zeros((1,), np.float32)))
    return (_ops.to_tensor(data[idx]),
            _ops.to_tensor(idx.reshape(-1, 1).astype(np.int64)),
            _ops.to_tensor(np.ones((len(idx),), np.float32)))


# -- doc/codegen machinery: API-compat no-ops --------------------------------


def autodoc(comment=""):
    def wrapper(func):
        return func

    return wrapper


def templatedoc(op_type=None):
    def wrapper(func):
        return func

    return wrapper


def deprecated(since=None, instead=None, reason=""):
    def wrapper(func):
        return func

    return wrapper


def generate_activation_fn(op_type):
    return getattr(_F, op_type)


def generate_layer_fn(op_type):
    return globals()[op_type]
