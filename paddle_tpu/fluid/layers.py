"""fluid.layers compatibility surface.

Ref: python/paddle/fluid/layers/* __all__ — the symbol set fluid-era
user code imports. Every name here resolves to the TPU-native
implementation; renamed ops get thin aliases (reduce_sum -> ops.sum,
fc -> Linear-on-the-fly, While/Switch -> lax-backed control flow).
Parameter-creating functions follow the fluid convention of creating
fresh parameters per call — call them while building a model/program.
"""
from __future__ import annotations

import numpy as np

from .. import ops as _ops
from ..core.tensor import Tensor
from ..nn import functional as _F
from ..nn.layers.common import Linear, Embedding
from ..nn.param_attr import ParamAttr
from ..static_ import data  # noqa: F401  (fluid.layers.data legacy)
from ..optim import lr as _lr

# -- wholesale re-exports: everything the functional namespaces already
# provide under the fluid name ----------------------------------------------
_g = globals()
for _src in (_ops, _F):
    for _n in dir(_src):
        if not _n.startswith("_") and _n not in _g:
            _g[_n] = getattr(_src, _n)

# decode / beam API lives in inference
from ..inference.decoder import (dynamic_decode, BeamSearchDecoder,  # noqa: F401,E402
                                 Decoder, beam_search, greedy_search)
from ..metrics import accuracy, Auc  # noqa: F401,E402
from ..ops.control_flow import (cond, while_loop, case,  # noqa: F401,E402
                                switch_case)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Streaming-free AUC of one batch (ref: metric_op.py auc)."""
    m = Auc(curve=curve, num_thresholds=num_thresholds)
    m.update(input, label)
    return m.accumulate()


# -- renamed reductions / elementwise ---------------------------------------
reduce_sum = _ops.sum
reduce_mean = _ops.mean
reduce_max = _ops.max
reduce_min = _ops.min
reduce_prod = _ops.prod
reduce_all = _ops.all
reduce_any = _ops.any
elementwise_add = _ops.add
elementwise_sub = _ops.subtract
elementwise_mul = _ops.multiply
elementwise_div = _ops.divide
elementwise_max = _ops.maximum
elementwise_min = _ops.minimum
elementwise_mod = _ops.remainder
elementwise_floordiv = _ops.floor_divide
elementwise_pow = _ops.pow
hard_sigmoid = _F.hardsigmoid
hard_swish = _F.hardswish
image_resize_short = None  # defined below
smooth_l1 = _F.smooth_l1_loss
kldiv_loss = _F.kl_div
sigmoid_cross_entropy_with_logits = _F.binary_cross_entropy_with_logits
warpctc = _F.ctc_loss
resize_bilinear = _ops.resize_bilinear
resize_nearest = _ops.resize_nearest
grid_sampler = _ops.grid_sample
uniform_random = _ops.uniform
gaussian_random = _ops.randn


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the short side equals out_short_len (ref: nn.py
    image_resize_short)."""
    h, w = input.shape[2], input.shape[3]
    short = min(h, w)
    oh = int(round(h * out_short_len / short))
    ow = int(round(w * out_short_len / short))
    return _ops.image_resize(input, out_shape=[oh, ow], resample=resample)


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """Fully-connected with fresh parameters (ref: nn.py fc). Flattens
    trailing dims past ``num_flatten_dims`` like the reference."""
    shp = input.shape
    in_dim = int(np.prod(shp[num_flatten_dims:]))
    x = _ops.reshape(input, list(shp[:num_flatten_dims]) + [in_dim])
    lin = Linear(in_dim, size, weight_attr=param_attr,
                 bias_attr=bias_attr)
    out = lin(x)
    if act is not None:
        out = getattr(_F, act)(out)
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """Embedding lookup with fresh table (ref: input.py embedding)."""
    emb = Embedding(size[0], size[1], padding_idx=padding_idx,
                    weight_attr=param_attr)
    return emb(input)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Standalone parameter (ref: tensor.py create_parameter)."""
    from ..nn.layer import Layer

    holder = Layer()
    return holder.create_parameter(shape, attr=attr, dtype=dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    return _ops.full(shape, value, dtype=dtype)


def create_tensor(dtype, name=None, persistable=False):
    return _ops.zeros([1], dtype=dtype)


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return _ops.full(shape, value, dtype=dtype)


def uniform_random_batch_size_like(input, shape, dtype="float32", min=-1.0,
                                   max=1.0, input_dim_idx=0,
                                   output_dim_idx=0, seed=0):
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return _ops.uniform(shape, dtype=dtype, min=min, max=max)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return _ops.randn(shape, dtype=dtype) * std + mean


def pad_constant_like(x, y, pad_value=0.0):
    """Pad y up to x's shape (ref: nn.py pad_constant_like)."""
    pads = []
    for xi, yi in zip(x.shape, y.shape):
        pads += [0, int(xi) - int(yi)]
    return _ops.pad(y, pads, value=pad_value)


def shape(input):
    return _ops.to_tensor(np.asarray(list(input.shape), np.int32))


def rank(input):
    return _ops.to_tensor(np.asarray(len(input.shape), np.int32))


def size(input):
    return _ops.to_tensor(np.asarray(int(np.prod(input.shape)), np.int64))


def range(start, end, step, dtype):  # noqa: A001 (fluid name)
    return _ops.arange(start, end, step, dtype=dtype)


def has_nan(x):
    return _ops.any(_ops.isnan(x))


def has_inf(x):
    return _ops.any(_ops.isinf(x))


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Host-side step counter (the reference keeps it in the scope)."""
    import itertools

    key = counter_name or "@STEP_COUNTER@"
    c = _counters.setdefault(key, itertools.count(begin, step))
    return _ops.to_tensor(np.asarray(next(c), np.int64))


_counters: dict = {}


# -- LR schedules under their fluid names (callable objects) ----------------
def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    return _lr.NoamDecay(d_model, warmup_steps, learning_rate)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    # fluid: lr * rate^(t / decay_steps)  ==  lr * (rate^(1/steps))^t
    return _lr.ExponentialDecay(learning_rate,
                                decay_rate ** (1.0 / decay_steps))


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    # fluid: lr * exp(-rate * t / decay_steps)
    return _lr.NaturalExpDecay(learning_rate, decay_rate / decay_steps)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    # fluid: lr / (1 + rate * t / decay_steps)
    return _lr.InverseTimeDecay(learning_rate, decay_rate / decay_steps)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False):
    return _lr.PolynomialDecay(learning_rate, decay_steps,
                               end_learning_rate, power, cycle)


def piecewise_decay(boundaries, values):
    return _lr.PiecewiseDecay(boundaries, values)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    return _lr.CosineAnnealingDecay(learning_rate,
                                    step_each_epoch * epochs)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    return _lr.LinearWarmup(learning_rate, warmup_steps, start_lr, end_lr)


# -- control flow under fluid names -----------------------------------------
While = while_loop
Switch = switch_case
IfElse = cond


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both"):
    """Debug print passthrough (ref: control_flow.py Print)."""
    import jax

    label = message or "Print"
    jax.debug.print(label + ": {x}", x=input._data
                    if hasattr(input, "_data") else input)
    return input
