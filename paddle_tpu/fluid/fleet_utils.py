"""Fleet utilities (ref: python/paddle/fluid/incubate/fleet/utils/
fleet_util.py, utils.py, hdfs.py).

FleetUtil's observability surface (rank-0 logging, metric zeroing,
global AUC over workers) and the program-inspection helpers are live;
the pslib/xbox model-donefile protocol is Baidu PS-serving plumbing and
raises the §4b descope error. HDFSClient is the contrib_utils one (a
real `hadoop fs` CLI wrapper, as in the reference).
"""
from __future__ import annotations

import logging
import os

import numpy as np

from .contrib_utils import HDFSClient  # noqa: F401 (ref utils/hdfs.py)
from .log_helper import get_logger

__all__ = ["FleetUtil", "HDFSClient", "program_type_trans",
           "check_saved_vars_try_dump", "parse_program",
           "check_pruned_program_vars", "graphviz"]

_logger = get_logger(__name__, logging.INFO,
                     fmt="%(asctime)s %(levelname)s: %(message)s")

_PSLIB_DESCOPE = (
    "the pslib/xbox model-donefile protocol is parameter-server serving "
    "plumbing (SURVEY §4b descope); checkpoint with framework.io "
    "save/load + save_inference_model")


class FleetUtil:
    """ref: fleet_util.py:53 — worker-fleet helper bundle."""

    def __init__(self, mode="collective"):
        if mode == "pslib":
            _logger.warning("pslib mode maps to collective on TPU "
                            "(SURVEY §4b)")

    # -- rank-0 logging -----------------------------------------------------
    def _is_first(self):
        from ..dist import env as denv

        return denv.get_rank() == 0

    def rank0_print(self, s):
        if self._is_first():
            print(s, flush=True)

    def rank0_info(self, s):
        if self._is_first():
            _logger.info(s)

    def rank0_error(self, s):
        if self._is_first():
            _logger.error(s)

    # -- metric helpers -----------------------------------------------------
    def set_zero(self, var_name, scope=None, place=None,
                 param_type="int64"):
        """Zero a scope variable in place (ref: fleet_util.py:121)."""
        from ..static_.program import global_scope

        scope = scope or global_scope()
        cur = scope.find_var(var_name)
        shape = np.shape(cur) if cur is not None else ()
        scope.set(var_name, np.zeros(shape, dtype=param_type))

    def get_global_auc(self, scope=None, stat_pos="_generated_var_2",
                       stat_neg="_generated_var_3"):
        """AUC from pos/neg bucket vars, summed across workers
        (ref: fleet_util.py:186). Buckets ride an all-reduce when a
        multi-process mesh is live; single-controller SPMD already sees
        global buckets."""
        from ..static_.program import global_scope

        scope = scope or global_scope()
        pos = scope.find_var(stat_pos)
        neg = scope.find_var(stat_neg)
        if pos is None or neg is None:
            self.rank0_print("not found auc bucket")
            return None
        pos = np.asarray(pos, dtype=np.float64).ravel()
        neg = np.asarray(neg, dtype=np.float64).ravel()
        from ..dist import env as denv

        if denv.get_world_size() > 1:
            from ..dist.collective import all_reduce

            pos = np.asarray(all_reduce(pos))
            neg = np.asarray(all_reduce(neg))
        # trapezoid area over the bucketed ROC (reference math)
        tot_pos = tot_neg = 0.0
        area = 0.0
        for i in range(len(pos) - 1, -1, -1):
            new_pos = tot_pos + pos[i]
            new_neg = tot_neg + neg[i]
            area += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        if tot_pos == 0.0 or tot_neg == 0.0:
            return 0.5
        return float(area / (tot_pos * tot_neg))

    def print_global_auc(self, scope=None, stat_pos="_generated_var_2",
                         stat_neg="_generated_var_3",
                         print_prefix=""):
        auc = self.get_global_auc(scope, stat_pos, stat_neg)
        self.rank0_print(f"{print_prefix} global auc = {auc}")

    # -- checkpointing ------------------------------------------------------
    def save_paddle_inference_model(self, executor, scope, program,
                                    feeded_vars, target_vars, output_path,
                                    day=None, pass_id=None, **kw):
        """Save an inference bundle under the day/pass layout
        (ref: fleet_util.py:876, minus the xbox upload)."""
        from .io import save_inference_model

        path = os.path.join(str(output_path), str(day or ""),
                            str(pass_id or "")).rstrip("/")
        os.makedirs(path, exist_ok=True)
        save_inference_model(
            path, [getattr(v, "name", v) for v in feeded_vars],
            target_vars, executor, main_program=program)
        return path

    def save_paddle_params(self, executor, scope, program, model_name,
                           output_path, day=None, pass_id=None, **kw):
        from .io import save_params

        path = os.path.join(str(output_path), str(day or ""),
                            str(pass_id or "")).rstrip("/")
        os.makedirs(path, exist_ok=True)
        save_params(executor, path, main_program=program,
                    filename=model_name)
        return path

    # -- pslib/xbox donefile protocol: recorded descope ---------------------
    def __getattr__(self, name):
        if name.startswith(("write_", "load_fleet", "save_fleet",
                            "save_xbox", "save_cache", "save_delta",
                            "get_last_save", "get_online_pass_interval",
                            "pull_all_dense", "save_batch_model",
                            "load_model", "save_model")):
            def _descoped(*a, **k):
                raise NotImplementedError(f"FleetUtil.{name}: "
                                          + _PSLIB_DESCOPE)

            return _descoped
        raise AttributeError(
            f"'FleetUtil' object has no attribute {name!r}")


# -- program inspection helpers (ref: fleet/utils/utils.py) -----------------

def program_type_trans(prog_dir, prog_fn, is_text):
    """Convert a saved program between text and binary forms
    (ref: utils.py:128). Our save_program writes json (text); the
    'binary' form is the same json — one serialization covers both, so
    this rewrites the file under the converted name."""
    from .incubate import load_program, save_program

    prog = load_program(os.path.join(prog_dir, prog_fn), is_text=is_text)
    out = prog_fn + (".bin" if is_text else ".pbtxt")
    save_program(prog, os.path.join(prog_dir, out))
    return out


def check_pruned_program_vars(train_prog, pruned_prog):
    """Check every var of the pruned program exists (with matching
    shape/dtype) in the train program (ref: utils.py:83)."""
    is_match = True
    train_vars = train_prog.global_block.vars
    for name, var in pruned_prog.global_block.vars.items():
        if name not in train_vars:
            _logger.warning(f"var {name} not in train program")
            is_match = False
            continue
        tv = train_vars[name]
        if tuple(tv.shape) != tuple(var.shape) or \
                str(tv.dtype) != str(var.dtype):
            _logger.warning(
                f"var {name} mismatch: train {tv.shape}/{tv.dtype} "
                f"vs pruned {var.shape}/{var.dtype}")
            is_match = False
    return is_match


def graphviz(block, output_dir="", filename="debug"):
    """Dot-file dump of a block's program (ref: utils.py:115; ours
    delegates to utils/debug.py program_to_dot)."""
    from ..utils.debug import program_to_dot

    dot = program_to_dot(block.program if hasattr(block, "program")
                         else block)
    path = os.path.join(output_dir or ".", filename + ".dot")
    with open(path, "w") as f:
        f.write(dot)
    return path


def parse_program(program, output_dir):
    """Write a human-readable summary of the program's vars/ops
    (ref: utils.py:381)."""
    os.makedirs(output_dir, exist_ok=True)
    path = os.path.join(output_dir, "program.txt")
    with open(path, "w") as f:
        f.write(program.to_string(throw_on_error=False)
                if hasattr(program, "to_string") else str(program))
    return path


def check_saved_vars_try_dump(dump_dir, dump_prog_fn, is_text_dump_program,
                              feed_config=None, fetch_config=None,
                              batch_size=1, save_filename=None):
    """Load a dumped program and sanity-check its persistable vars
    (ref: utils.py:359 — the load/inspect half; the feed/fetch replay
    belongs to inference.Predictor)."""
    from .incubate import load_program

    prog = load_program(os.path.join(dump_dir, dump_prog_fn),
                        is_text=is_text_dump_program)
    persist = [v for v in prog.global_block.vars.values()
               if getattr(v, "persistable", False)]
    _logger.info(f"persistable vars: {[v.name for v in persist]}")
    return prog, persist
