"""fluid.dataset: DatasetFactory / InMemoryDataset / QueueDataset
(ref: python/paddle/fluid/dataset.py:22,325,847).

The reference wires these to the C++ MultiSlotDataset + the PS-era
multi-threaded trainer; here they are real host-side slot-file readers
feeding `Executor.train_from_dataset` batches of the exact static-graph
feed shapes. Kept: the MultiSlot text format (count-prefixed values per
slot, one sample per line, in `set_use_var` order), pipe commands
(each file is streamed through the command, as the reference does),
local/global shuffle, batching. The XLA executor replaces the
device-worker thread pool: `thread_num` is accepted and recorded, but a
single compiled program consumes the batches.

Line format per sample (MultiSlotDataFeed):
    <n0> v0_1 ... v0_n0  <n1> v1_1 ... v1_n1  ...
one count-prefixed group per slot; dense slots must supply exactly
prod(sample_shape) values.
"""
from __future__ import annotations

import subprocess

import numpy as np

__all__ = ["DatasetFactory", "DatasetBase", "InMemoryDataset",
           "QueueDataset"]


class DatasetFactory:
    """ref dataset.py:22 — create_dataset by class name."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        try:
            cls = {"InMemoryDataset": InMemoryDataset,
                   "QueueDataset": QueueDataset}[datafeed_class]
        except KeyError:
            raise ValueError(
                f"datafeed class {datafeed_class} does not exist")
        return cls()


class _Slot:
    def __init__(self, name, sample_shape, dtype):
        self.name = name
        self.sample_shape = tuple(int(abs(s)) for s in sample_shape)
        self.size = int(np.prod(self.sample_shape)) if self.sample_shape \
            else 1
        self.dtype = dtype


class DatasetBase:
    """ref dataset.py:64 DatasetBase."""

    def __init__(self):
        self.pipe_command = "cat"
        self.thread_num = 1
        self.batch_size = 1
        self.filelist = []
        self.slots = []
        self.hdfs_config = None
        self._rows = None  # parsed samples: list of per-slot arrays

    # -- configuration (reference surface) ---------------------------------
    def set_pipe_command(self, pipe_command):
        self.pipe_command = pipe_command

    def set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self.thread_num = int(thread_num)

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def set_hdfs_config(self, fs_name, fs_ugi):
        self.hdfs_config = (fs_name, fs_ugi)

    def set_use_var(self, var_list):
        """Declare the feed variables, in slot order (ref dataset.py:224).
        float32 and int ("int64") dtypes only, like the reference."""
        self.slots = []
        for var in var_list:
            dt = str(np.dtype(getattr(var, "dtype", np.float32)))
            if dt.startswith("float"):
                dtype = np.float32
            elif dt.startswith("int") or dt.startswith("uint"):
                dtype = np.int64
            else:
                raise ValueError(
                    "fluid.dataset only supports dtype=float32 and "
                    f"dtype=int64, got {dt} for {var.name}")
            shape = tuple(getattr(var, "shape", ()) or ())
            self.slots.append(_Slot(var.name, shape[1:], dtype))

    def desc(self):
        """Text description (reference returns the proto text)."""
        return "\n".join(
            [f"pipe_command: {self.pipe_command}",
             f"batch_size: {self.batch_size}",
             f"thread_num: {self.thread_num}"] +
            [f"slot: {s.name} shape={s.sample_shape} "
             f"dtype={np.dtype(s.dtype).name}" for s in self.slots])

    # -- reading -----------------------------------------------------------
    def _read_file_bytes(self, path):
        if self.pipe_command and self.pipe_command != "cat":
            # the reference streams every file through the user's pipe
            # command; same here (stdin=file, stdout=samples)
            with open(path, "rb") as f:
                proc = subprocess.run(
                    self.pipe_command, shell=True, stdin=f,
                    capture_output=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"pipe_command {self.pipe_command!r} failed on "
                    f"{path}: {proc.stderr.decode()[:500]}")
            return proc.stdout
        with open(path, "rb") as f:
            return f.read()

    def _parse_line(self, line, path):
        toks = line.split()
        out = []
        i = 0
        for slot in self.slots:
            if i >= len(toks):
                raise ValueError(
                    f"{path}: line ran out of tokens at slot "
                    f"{slot.name!r}: {line[:80]!r}")
            n = int(toks[i])
            i += 1
            vals = toks[i:i + n]
            if len(vals) != n:
                raise ValueError(
                    f"{path}: slot {slot.name!r} declares {n} values, "
                    f"found {len(vals)}: {line[:80]!r}")
            i += n
            if slot.size != n:
                raise ValueError(
                    f"{path}: dense slot {slot.name!r} needs "
                    f"{slot.size} values (shape {slot.sample_shape}), "
                    f"got {n}")
            arr = np.asarray(vals, dtype=slot.dtype)
            out.append(arr.reshape(slot.sample_shape) if slot.sample_shape
                       else arr.reshape(()))
        if i != len(toks):
            # reference MultiSlotDataFeed: a line must contain exactly
            # its slots (same strictness as the native parser)
            raise ValueError(
                f"{path}: {len(toks) - i} trailing tokens after the "
                f"last slot: {line[:80]!r}")
        return out

    def _iter_samples(self):
        if not self.slots:
            raise RuntimeError("call set_use_var(...) before reading")
        for path in self.filelist:
            raw = self._read_file_bytes(path)
            native = self._parse_native(raw, path)
            if native is not None:
                n = native[0].shape[0] if native else 0
                for j in range(n):
                    yield [native[i][j].reshape(s.sample_shape)
                           for i, s in enumerate(self.slots)]
                continue
            for line in raw.decode().splitlines():
                if line.strip():
                    yield self._parse_line(line, path)

    def _parse_native(self, raw, path):
        """C++ MultiSlot parser (runtime/cc pt_multislot_parse — the
        reference data_feed.cc role) over the RAW file bytes, so format
        errors carry real line numbers; None -> Python fallback."""
        try:
            from ..runtime import multislot_parse

            out = multislot_parse(
                raw, [s.size for s in self.slots],
                [s.dtype == np.float32 for s in self.slots])
        except ValueError as e:
            raise ValueError(f"{path}: {e}") from None
        except Exception:
            return None
        return out

    def _batches(self, samples, drop_last=True):
        buf = []
        self.last_dropped = 0
        for s in samples:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield self._stack(buf)
                buf = []
        if buf:
            if drop_last:
                # static programs bake concrete feed shapes, so a ragged
                # tail can't run through the same executable; record the
                # drop so the executor can say so out loud
                self.last_dropped = len(buf)
            else:
                yield self._stack(buf)

    def _stack(self, buf):
        return {slot.name: np.stack([row[j] for row in buf])
                for j, slot in enumerate(self.slots)}

    def _iter_file_matrices(self):
        """Per file: slot matrices [(n_samples, slot.size) arrays] —
        native-parsed when possible, Python-parsed otherwise."""
        for path in self.filelist:
            raw = self._read_file_bytes(path)
            mats = self._parse_native(raw, path)
            if mats is None:
                rows = [self._parse_line(line, path)
                        for line in raw.decode().splitlines()
                        if line.strip()]
                mats = [np.stack([r[i].reshape(-1) for r in rows])
                        if rows else
                        np.empty((0, s.size), s.dtype)
                        for i, s in enumerate(self.slots)]
            yield mats

    def iter_batches(self, drop_last=True):
        """Batched feed dicts {var_name: (B, *sample_shape) array}.

        Streams batch-contiguous SLICES of the parsed per-file matrices
        (no per-sample Python loop — the point of the native parser);
        a leftover tail carries across file boundaries."""
        if not self.slots:
            raise RuntimeError("call set_use_var(...) before reading")
        B = self.batch_size
        self.last_dropped = 0
        carry = [np.empty((0, s.size), s.dtype) for s in self.slots]
        for mats in self._iter_file_matrices():
            carry = [np.concatenate([c, m]) if c.shape[0] else m
                     for c, m in zip(carry, mats)]
            n = carry[0].shape[0]
            k = 0
            while n - k >= B:
                yield {s.name:
                       carry[i][k:k + B].reshape((B,) + s.sample_shape)
                       for i, s in enumerate(self.slots)}
                k += B
            if k:
                carry = [c[k:] for c in carry]
        tail = carry[0].shape[0]
        if tail:
            if drop_last:
                self.last_dropped = tail
            else:
                yield {s.name:
                       carry[i].reshape((tail,) + s.sample_shape)
                       for i, s in enumerate(self.slots)}


class QueueDataset(DatasetBase):
    """ref dataset.py:847 — streaming: every pass re-reads the files."""

    def local_shuffle(self):
        raise NotImplementedError(
            "QueueDataset streams files and cannot shuffle; use "
            "InMemoryDataset.local_shuffle (ref dataset.py:897 raises "
            "the same way)")

    def global_shuffle(self, fleet=None):
        self.local_shuffle()


class InMemoryDataset(DatasetBase):
    """ref dataset.py:325 — load once, shuffle in memory."""

    def __init__(self):
        super().__init__()
        self._seed = None

    def load_into_memory(self):
        self._rows = list(self._iter_samples())

    def set_shuffle_seed(self, seed):
        self._seed = int(seed)

    def local_shuffle(self):
        if self._rows is None:
            raise RuntimeError("call load_into_memory() first")
        rng = np.random.RandomState(self._seed)
        rng.shuffle(self._rows)

    def global_shuffle(self, fleet=None, thread_num=12):
        # single-host collective world: global == local (the PS fleet
        # shuffle service is descoped, SURVEY §4b)
        self.local_shuffle()

    def release_memory(self):
        self._rows = None

    def get_memory_data_size(self, fleet=None):
        return len(self._rows or [])

    def iter_batches(self, drop_last=True):
        if self._rows is None:  # not loaded: stream the fast base path
            yield from super().iter_batches(drop_last=drop_last)
            return
        yield from self._batches(iter(self._rows), drop_last=drop_last)
