"""fluid.log_helper (ref: python/paddle/fluid/log_helper.py).

Logger factory that never touches logging.basicConfig (importing the
framework must not globally reconfigure the user's logging).
"""
from __future__ import annotations

import logging

__all__ = ["get_logger"]


def get_logger(name, level, fmt=None):
    """Named logger with its own handler; repeated calls don't stack
    duplicate handlers (same guarantee the reference gives by building
    the handler once per call site)."""
    logger = logging.getLogger(name)
    logger.setLevel(level)
    if not any(getattr(h, "_paddle_tpu_handler", False)
               for h in logger.handlers):
        handler = logging.StreamHandler()
        handler._paddle_tpu_handler = True
        if fmt:
            handler.setFormatter(logging.Formatter(fmt=fmt))
        logger.addHandler(handler)
    logger.propagate = False
    return logger
