"""fluid.average (ref: python/paddle/fluid/average.py).

Pure-python running weighted mean; deprecated in the reference in favour
of fluid.metrics but still part of the fluid surface.
"""
from __future__ import annotations

import warnings

import numpy as np

__all__ = ["WeightedAverage"]


class WeightedAverage:
    """Running weighted average (ref: average.py:40). ``add`` accepts a
    scalar or ndarray value with a scalar weight; ``eval`` returns
    numerator/denominator."""

    def __init__(self):
        warnings.warn(
            f"{type(self).__name__} is deprecated; use metrics.Accuracy "
            "or a plain running mean", Warning)
        self.reset()

    def reset(self):
        self.numerator = None
        self.denominator = None

    def add(self, value, weight):
        if isinstance(value, np.ndarray) and value.shape == (1,):
            value = float(value[0])
        if not isinstance(value, (int, float, np.ndarray)):
            raise ValueError("value must be a number or numpy ndarray")
        if not isinstance(weight, (int, float)):
            raise ValueError("weight must be a number")
        if self.numerator is None:
            self.numerator = value * weight
            self.denominator = weight
        else:
            self.numerator += value * weight
            self.denominator += weight

    def eval(self):
        if self.numerator is None or not self.denominator:
            raise ValueError("eval() before add(): nothing accumulated")
        return self.numerator / self.denominator
