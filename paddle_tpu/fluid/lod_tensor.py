"""LoDTensor construction helpers (ref: python/paddle/fluid/lod_tensor.py).

The reference's LoDTensor carries a level-of-detail offset table beside a
flattened buffer; this framework's convention is dense data + explicit
per-sequence lengths (SURVEY §3), so ``LoDTensor`` here is a thin record
of (ndarray, recursive_seq_lens) that converts freely to/from the dense
representation the ops consume.
"""
from __future__ import annotations

import numpy as np

__all__ = ["LoDTensor", "LoDTensorArray", "create_lod_tensor",
           "create_random_int_lodtensor"]


def _lens_to_offsets(lens):
    off = [0]
    for n in lens:
        off.append(off[-1] + int(n))
    return off


class LoDTensor:
    """Flattened buffer + recursive sequence lengths (ref: core LoDTensor,
    python interface in fluid/lod_tensor.py). ``lod()`` returns the
    offset-form table the reference exposes; ``recursive_sequence_lengths``
    the length form."""

    def __init__(self, data=None, recursive_seq_lens=None):
        self._data = None if data is None else np.asarray(data)
        self._seq_lens = [list(map(int, lv))
                          for lv in (recursive_seq_lens or [])]

    # reference-core API surface -------------------------------------------
    def set(self, data, place=None):
        self._data = np.asarray(data)

    def set_recursive_sequence_lengths(self, lens):
        self._seq_lens = [list(map(int, lv)) for lv in lens]

    def recursive_sequence_lengths(self):
        return [list(lv) for lv in self._seq_lens]

    def set_lod(self, lod):
        self._seq_lens = [list(np.diff(lv).astype(int)) for lv in lod]

    def lod(self):
        return [_lens_to_offsets(lv) for lv in self._seq_lens]

    def has_valid_recursive_sequence_lengths(self):
        if not self._seq_lens:
            return True
        # each deeper level must partition the one above; the last level
        # must partition the rows of the buffer
        for above, below in zip(self._seq_lens, self._seq_lens[1:]):
            if len(below) != sum(above):
                return False
        n_rows = 0 if self._data is None else self._data.shape[0]
        return sum(self._seq_lens[-1]) == n_rows

    def shape(self):
        return [] if self._data is None else list(self._data.shape)

    def __array__(self, dtype=None):
        arr = np.zeros((0,)) if self._data is None else self._data
        return arr.astype(dtype) if dtype is not None else arr

    def __repr__(self):
        return (f"LoDTensor(shape={self.shape()}, "
                f"recursive_seq_lens={self._seq_lens})")


class LoDTensorArray(list):
    """ref: core.LoDTensorArray — a growable list of LoDTensors; python
    list semantics are exactly the TensorArray contract here."""

    def append(self, t):  # accept raw ndarrays for convenience
        super().append(t if isinstance(t, LoDTensor) else LoDTensor(t))


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """Build a LoDTensor from an ndarray / nested list / LoDTensor
    (ref: fluid/lod_tensor.py create_lod_tensor). Nested-list input is
    flattened to a column the way the reference does."""
    if isinstance(data, LoDTensor):
        return create_lod_tensor(np.asarray(data), recursive_seq_lens, place)
    if isinstance(data, list):
        flat = [x for seq in data for x in seq]
        arr = np.asarray(flat)
        if arr.ndim == 1:
            arr = arr.reshape(-1, 1)
        inferred = [[len(seq) for seq in data]]
        if recursive_seq_lens is None:
            recursive_seq_lens = inferred
        return LoDTensor(arr, recursive_seq_lens)
    arr = np.asarray(data)
    t = LoDTensor(arr, recursive_seq_lens)
    if not t.has_valid_recursive_sequence_lengths():
        raise ValueError(
            f"recursive_seq_lens {recursive_seq_lens} do not partition the "
            f"{arr.shape[0]} rows of data")
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=1):
    """ref: fluid/lod_tensor.py create_random_int_lodtensor: total rows =
    sum of the last-level lengths, element shape = base_shape."""
    rows = int(sum(recursive_seq_lens[-1]))
    shape = [rows] + list(base_shape)
    data = np.random.randint(low, high + 1, size=shape).astype(np.int64)
    return LoDTensor(data, recursive_seq_lens)
