"""fluid.contrib compatibility surface.

Refs: python/paddle/fluid/contrib/ —
- layers/rnn_impl.py: BasicGRUUnit/basic_gru/BasicLSTMUnit/basic_lstm
- layers/nn.py: fused_elemwise_activation, sequence_topk_avg_pooling,
  var_conv_2d, match_matrix_tensor, fused_embedding_seq_pool,
  multiclass_nms2, shuffle_batch, partial_concat, partial_sum,
  tdm_child, rank_attention, search_pyramid_hash
- layers/metric_op.py: ctr_metric_bundle
- mixed_precision/: AutoMixedPrecisionLists, decorate (live in amp/)
- slim/quantization/: PostTrainingQuantization, WeightQuantization
  (live in quant/)
- extend_optimizer/: extend_with_decoupled_weight_decay
- reader/distributed_reader.py: distributed_batch_reader
- memory_usage_calc.py / op_frequence.py: program introspection

Dense/静态-shape conventions as everywhere: LoD inputs become padded
tensors + lengths.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .. import ops as _ops
from ..core.tensor import Tensor
from ..nn import functional as _F
from ..nn.layer import Layer
from ..ops._base import register, apply, unwrap

# re-exports from the native homes
from ..amp import AutoMixedPrecisionLists, decorate  # noqa: F401
from ..quant import (PostTrainingQuantization,  # noqa: F401
                     quantize_inference_model)  # noqa: F401
from ..ops.misc import tree_conv  # noqa: F401
from .rnn import _FluidGRUCell, _gru_step

__all__ = [
    "BasicGRUUnit", "basic_gru", "BasicLSTMUnit", "basic_lstm",
    "fused_elemwise_activation", "sequence_topk_avg_pooling",
    "var_conv_2d", "match_matrix_tensor", "fused_embedding_seq_pool",
    "multiclass_nms2", "shuffle_batch", "partial_concat", "partial_sum",
    "tdm_child", "rank_attention", "search_pyramid_hash",
    "ctr_metric_bundle", "AutoMixedPrecisionLists", "decorate",
    "PostTrainingQuantization", "WeightQuantization",
    "extend_with_decoupled_weight_decay", "distributed_batch_reader",
    "memory_usage", "op_freq_statistic", "tree_conv",
]


# -- basic RNN units (ref: contrib/layers/rnn_impl.py) ----------------------


class BasicGRUUnit(Layer):
    """ref: rnn_impl.py BasicGRUUnit — raw GRU step cell.

    The input projection is built on first forward (the reference's
    _build_once behavior): run one forward BEFORE handing parameters()
    to an optimizer, or pass ``input_size`` to build eagerly."""

    def __init__(self, name_scope=None, hidden_size=None, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 dtype="float32"):
        super().__init__()
        if hidden_size is None:  # fluid passes (name_scope, hidden)
            hidden_size = name_scope
        self.cell = _FluidGRUCell(hidden_size, param_attr, bias_attr,
                                  "sigmoid", "tanh", False)
        self.hidden_size = hidden_size
        # input projection (BasicGRUUnit takes raw features)
        self._proj = None
        self._param_attr = param_attr

    def forward(self, input, pre_hidden):
        from .layers import fc

        if self._proj is None:
            from ..nn.layers.common import Linear

            self._proj = Linear(int(input.shape[-1]),
                                3 * self.hidden_size,
                                weight_attr=self._param_attr)
        x = self._proj(input)
        new_h, _, _ = _gru_step(self.cell, x, pre_hidden, "sigmoid",
                                "tanh", False)
        return new_h


def basic_gru(input, init_hidden, hidden_size, num_layers=1,
              sequence_length=None, dropout_prob=0.0, bidirectional=False,
              batch_first=True, param_attr=None, bias_attr=None,
              gate_activation=None, activation=None, dtype="float32",
              name="basic_gru"):
    """Stacked GRU (ref: rnn_impl.py basic_gru). Returns
    (output_seq, last_hidden (L*dirs, B, H)).

    Creates fresh parameters per call — the fluid build-time convention
    (same as ``fluid.layers.fc``): call while building a static Program,
    or hold an ``nn.layers.GRU`` module for eager training."""
    from ..nn.layers.rnn import GRU

    x = input if batch_first else _ops.transpose(input, [1, 0, 2])
    net = GRU(int(x.shape[-1]), hidden_size, num_layers=num_layers,
              direction="bidirect" if bidirectional else "forward",
              dropout=dropout_prob)
    out, h = net(x, init_hidden, sequence_length=sequence_length)
    if not batch_first:
        out = _ops.transpose(out, [1, 0, 2])
    return out, h


class BasicLSTMUnit(Layer):
    """ref: rnn_impl.py BasicLSTMUnit — raw LSTM step cell over
    concat([x, h]) with a forget-gate bias."""

    def __init__(self, name_scope=None, hidden_size=None, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 forget_bias=1.0, dtype="float32"):
        super().__init__()
        if hidden_size is None:
            hidden_size = name_scope
        self.hidden_size = hidden_size
        self.forget_bias = forget_bias
        self._lin = None
        self._param_attr = param_attr
        self._bias_attr = bias_attr

    def forward(self, input, pre_hidden, pre_cell):
        if self._lin is None:
            from ..nn.layers.common import Linear

            self._lin = Linear(
                int(input.shape[-1]) + self.hidden_size,
                4 * self.hidden_size, weight_attr=self._param_attr,
                bias_attr=self._bias_attr)
        H = self.hidden_size
        g = self._lin(_ops.concat([input, pre_hidden], axis=-1))
        i, f, c_cand, o = (g[:, :H], g[:, H:2 * H], g[:, 2 * H:3 * H],
                           g[:, 3 * H:])
        new_c = _F.sigmoid(f + self.forget_bias) * pre_cell + \
            _F.sigmoid(i) * _F.tanh(c_cand)
        new_h = _F.sigmoid(o) * _F.tanh(new_c)
        return new_h, new_c


def basic_lstm(input, init_hidden, init_cell, hidden_size, num_layers=1,
               sequence_length=None, dropout_prob=0.0, bidirectional=False,
               batch_first=True, param_attr=None, bias_attr=None,
               gate_activation=None, activation=None, forget_bias=1.0,
               dtype="float32", name="basic_lstm"):
    """Stacked LSTM (ref: rnn_impl.py basic_lstm). Returns
    (output_seq, last_hidden, last_cell).

    Creates fresh parameters per call (fluid build-time convention, as
    ``fc``); hold an ``nn.layers.LSTM`` module for eager training."""
    from ..nn.layers.rnn import LSTM

    x = input if batch_first else _ops.transpose(input, [1, 0, 2])
    net = LSTM(int(x.shape[-1]), hidden_size, num_layers=num_layers,
               direction="bidirect" if bidirectional else "forward",
               dropout=dropout_prob)
    init = None if init_hidden is None else (init_hidden, init_cell)
    out, (h, c) = net(x, init, sequence_length=sequence_length)
    if not batch_first:
        out = _ops.transpose(out, [1, 0, 2])
    return out, h, c


# -- fused / CTR / text-matching ops (ref: contrib/layers/nn.py) ------------


def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    """ref: fused_elemwise_activation_op. XLA fuses elementwise chains
    natively; this applies functor_list right-to-left."""
    fns = {"elementwise_add": lambda a, b: a + b,
           "elementwise_mul": lambda a, b: a * b,
           "relu": lambda a: _F.relu(a),
           "scale": lambda a: a * scale,
           "tanh": lambda a: _F.tanh(a),
           "sigmoid": lambda a: _F.sigmoid(a)}
    f0, f1 = functor_list[0], functor_list[1]
    if f1.startswith("elementwise"):
        inner = fns[f1](x, y)
        return fns[f0](inner) if f0 not in ("elementwise_add",
                                            "elementwise_mul") \
            else fns[f0](inner, y)
    inner = fns[f1](y)
    return fns[f0](x, inner)


@register("seq_topk_avg_pool")
def _seq_topk_avg_pool(x, lengths, *, topks):
    # x (B, C, L) scores; per channel, average of the top-k valid entries
    B, C, L = x.shape
    mask = (jnp.arange(L)[None, :] < lengths[:, None])[:, None, :]
    neg = jnp.where(mask, x, -jnp.inf)
    srt = jnp.sort(neg, axis=-1)[..., ::-1]              # desc
    outs = []
    for k in topks:
        top = srt[..., :k]
        finite = jnp.isfinite(top)
        s = jnp.where(finite, top, 0.0).sum(-1)
        outs.append(s / jnp.maximum(finite.sum(-1), 1))
    return jnp.stack(outs, axis=-1).reshape(B, C * len(topks))


def sequence_topk_avg_pooling(input, row, col, topks, channel_num,
                              lengths=None):
    """Top-k average pooling per channel over variable-length score rows
    (ref: contrib/layers/nn.py sequence_topk_avg_pooling). Dense form:
    input (B, C, L) + lengths (B,)."""
    if lengths is None:
        L = unwrap(input).shape[-1]
        lengths = Tensor(jnp.full((unwrap(input).shape[0],), L, jnp.int32),
                         _internal=True)
    return apply("seq_topk_avg_pool", input, lengths,
                 topks=tuple(int(k) for k in topks))


def var_conv_2d(input, row, col, input_channel, output_channel, filter_size,
                stride=1, param_attr=None, act=None, dtype="float32",
                name=None, weight=None, lengths=None):
    """Variable-size 2-D conv (ref: var_conv_2d_op): each row's image has
    its own (h, w). Dense form: input (B, C, H, W) padded + per-row
    (h, w) in ``row``/``col``; padding is masked out before the conv so
    results match per-image convs."""
    x = unwrap(input)
    B, C, H, W = x.shape
    hs = unwrap(row).reshape(-1)
    ws = unwrap(col).reshape(-1)
    ym = jnp.arange(H)[None, :] < hs[:, None]
    xm = jnp.arange(W)[None, :] < ws[:, None]
    mask = (ym[:, :, None] & xm[:, None, :])[:, None]
    masked = Tensor(jnp.where(mask, x, 0.0), _internal=True)
    if weight is None:
        raise ValueError("pass weight=(O, C, k, k)")
    out = _F.conv2d(masked, weight, stride=stride,
                    padding=(int(filter_size) - 1) // 2)
    if act is not None:
        out = getattr(_F, act)(out)
    return out


@register("match_matrix")
def _match_matrix(x, y, w):
    # x (B, Lx, D), y (B, Ly, D), w (D, C, D) -> (B, C, Lx, Ly)
    return jnp.einsum("bxd,dce,bye->bcxy", x, w, y)


def match_matrix_tensor(x, y, channel_num, act=None, param_attr=None,
                        dtype="float32", name=None, weight=None):
    """Text-match similarity cube (ref: match_matrix_tensor_op):
    out[b, c] = X W_c Y^T. Functional: pass weight (D, C, D). Returns
    (out (B, C, Lx, Ly), out)."""
    if weight is None:
        raise ValueError("pass weight=(D, channel_num, D)")
    out = apply("match_matrix", x, y, weight)
    if act is not None:
        out = getattr(_F, act)(out)
    return out, out


@register("fused_emb_seq_pool")
def _fused_emb_seq_pool(table, ids, lengths, *, combiner):
    # ids (B, L) -> lookup + masked sum/mean over L
    emb = table[ids.astype(jnp.int32)]                   # (B, L, D)
    mask = (jnp.arange(ids.shape[1])[None, :] <
            lengths[:, None])[..., None]
    s = jnp.where(mask, emb, 0.0).sum(axis=1)
    if combiner == "mean":
        s = s / jnp.maximum(lengths[:, None], 1).astype(s.dtype)
    return s


def fused_embedding_seq_pool(input, size=None, is_sparse=False,
                             padding_idx=None, combiner="sum",
                             param_attr=None, dtype="float32", weight=None,
                             lengths=None):
    """Embedding lookup fused with sequence sum/mean pool (ref:
    fused_embedding_seq_pool_op). Functional: pass weight (V, D);
    input (B, L) ids + lengths."""
    if weight is None:
        raise ValueError("pass weight=(V, D)")
    ids = input
    if lengths is None:
        L = unwrap(ids).shape[1]
        lengths = Tensor(jnp.full((unwrap(ids).shape[0],), L, jnp.int32),
                         _internal=True)
    return apply("fused_emb_seq_pool", weight, ids, lengths,
                 combiner=combiner)


def multiclass_nms2(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                    nms_threshold=0.3, normalized=True, nms_eta=1.0,
                    background_label=0, return_index=True, name=None):
    """multiclass_nms that also returns selection indices (ref:
    multiclass_nms2 op). Index is the flat (class * M + original box)
    candidate id per kept row, -1 padded — computed inside the NMS
    kernel, not reconstructed after."""
    from ..ops.detection import multiclass_nms_with_index

    out, index, counts = multiclass_nms_with_index(
        bboxes, scores, score_threshold, nms_top_k, keep_top_k,
        nms_threshold, normalized, nms_eta, background_label)
    if not return_index:
        return out, counts
    return out, index, counts


def shuffle_batch(x, seed=None):
    """Random batch-row permutation (ref: shuffle_batch_op); a fixed
    ``seed`` gives a reproducible permutation."""
    from ..core import random as prandom

    n = unwrap(x).shape[0]
    key = jax.random.PRNGKey(int(seed)) if seed is not None \
        else prandom.next_key()
    perm = jax.random.permutation(key, n)
    return Tensor(unwrap(x)[perm], _internal=True)


def partial_concat(input, start_index=0, length=-1):
    """Concat a feature slice of every input (ref: partial_concat_op)."""
    parts = []
    for t in input:
        d = unwrap(t).shape[1]
        end = d if length < 0 else start_index + length
        parts.append(unwrap(t)[:, start_index:end])
    return Tensor(jnp.concatenate(parts, axis=1), _internal=True)


def partial_sum(input, start_index=0, length=-1):
    """Sum a feature slice across inputs (ref: partial_sum_op)."""
    acc = None
    for t in input:
        d = unwrap(t).shape[1]
        end = d if length < 0 else start_index + length
        sl = unwrap(t)[:, start_index:end]
        acc = sl if acc is None else acc + sl
    return Tensor(acc, _internal=True)


def tdm_child(x, node_nums, child_nums, param_attr=None, dtype="int32",
              tree_info=None):
    """Tree-index child lookup (ref: tdm_child_op, tree-based deep
    match): for each node id, return its children ids and a leaf mask.
    ``tree_info`` (node_nums, 3 + child_nums): [item_id, layer, parent,
    child_0..child_n] (0 = none)."""
    if tree_info is None:
        raise ValueError("pass tree_info=(node_nums, 3 + child_nums)")
    info = unwrap(tree_info).astype(jnp.int32)
    ids = unwrap(x).astype(jnp.int32).reshape(-1)
    children = info[ids, 3:3 + child_nums]               # (N, child)
    item_ids = info[children, 0]
    leaf_mask = ((children != 0) & (item_ids != 0)).astype(jnp.int32)
    shp = list(unwrap(x).shape) + [child_nums]
    return (Tensor(children.reshape(shp), _internal=True),
            Tensor(leaf_mask.reshape(shp), _internal=True))


@register("rank_attention")
def _rank_attention(x, rank_offset, rank_param, *, max_rank):
    # x (B, D); rank_offset (B, >=1) with rank id in col 0;
    # rank_param (max_rank * max_rank, D, out) the per-(rank, rank) block
    B, D = x.shape
    out_dim = rank_param.shape[-1]
    rank = jnp.clip(rank_offset[:, 0].astype(jnp.int32), 0, max_rank - 1)
    # per-sample block-diag attention: use the (rank, rank) block
    block = rank_param.reshape(max_rank, max_rank, D, out_dim)
    w = block[rank, rank]                                # (B, D, out)
    return jnp.einsum("bd,bdo->bo", x, w)


def rank_attention(input, rank_offset, rank_param_shape, rank_param_attr,
                   max_rank=3, max_size=0, rank_param=None):
    """CTR rank attention (ref: rank_attention_op): per-sample parameter
    block selected by its rank feature. Functional: pass
    ``rank_param (max_rank*max_rank, D, out)``."""
    if rank_param is None:
        raise ValueError("pass rank_param=(max_rank*max_rank, D, out)")
    return apply("rank_attention", input, rank_offset, rank_param,
                 max_rank=int(max_rank))


def search_pyramid_hash(input, num_emb, space_len, pyramid_layer, rand_len,
                        drop_out_percent=0.0, is_training=False,
                        use_filter=False, white_list_len=0,
                        black_list_len=0, seed=0, lr=1.0, param_attr=None,
                        param_attr_wl=None, param_attr_bl=None, name=None,
                        distribute_update_vars=None, embedding=None,
                        lengths=None):
    """Pyramid-hash embedding (ref: search_pyramid_hash op, CTR text
    match): every n-gram (n = 2..pyramid_layer) of the id sequence is
    hashed into ``embedding (space_len, rand_len)`` and the pieces
    concatenate to num_emb per position, sum-pooled over the sequence.
    Functional: pass ``embedding``."""
    if embedding is None:
        raise ValueError("pass embedding=(space_len, rand_len)")
    ids = unwrap(input).astype(jnp.uint32)               # (B, L)
    table = unwrap(embedding)
    B, L = ids.shape
    pieces = num_emb // rand_len
    out = jnp.zeros((B, num_emb), table.dtype)
    for n in range(2, pyramid_layer + 1):
        if L < n:
            break
        # rolling n-gram keys
        key = jnp.zeros((B, L - n + 1), jnp.uint32)
        for j in range(n):
            key = key * jnp.uint32(1000003) + ids[:, j:L - n + 1 + j]
        for p in range(pieces):
            mul = jnp.uint32(2654435761) * jnp.uint32(2 * p + 1) | \
                jnp.uint32(1)
            slot = (key * mul) % jnp.uint32(table.shape[0])
            emb = table[slot.astype(jnp.int32)]          # (B, Lg, rand)
            out = out.at[:, p * rand_len:(p + 1) * rand_len].add(
                emb.sum(axis=1))
    return Tensor(out, _internal=True)


def ctr_metric_bundle(input, label):
    """CTR aggregate stats (ref: contrib/layers/metric_op.py
    ctr_metric_bundle): returns (local_sqrerr, local_abserr, local_prob,
    local_q, local_pos_num, local_ins_num)."""
    p = unwrap(input).astype(jnp.float32).reshape(-1)
    y = unwrap(label).astype(jnp.float32).reshape(-1)
    sqrerr = jnp.sum((p - y) ** 2)
    abserr = jnp.sum(jnp.abs(p - y))
    prob = jnp.sum(p)
    q = jnp.sum(p / jnp.maximum(1.0 - p, 1e-6))
    pos = jnp.sum(y)
    n = jnp.asarray(float(p.shape[0]))
    return tuple(Tensor(v, _internal=True)
                 for v in (sqrerr, abserr, prob, q, pos, n))


# -- slim / optimizer / reader extras ---------------------------------------


class WeightQuantization:
    """Weight-only int8/int16 quantization of a saved state dict (ref:
    slim/quantization/post_training_quantization.py WeightQuantization)."""

    def __init__(self, model_dir, model_filename=None,
                 params_filename=None, state_dict=None):
        self._state = state_dict
        self._dir = model_dir

    def quantize_weight_to_int(self, save_model_dir=None,
                               weight_bits=8, quantizable_op_type=None,
                               weight_quantize_type="channel_wise_abs_max",
                               generate_test_model=False):
        from ..quant import quantize_abs_max

        state = self._state
        if state is None:
            import paddle_tpu as _pt

            state = _pt.load(self._dir)
        channel_axis = 0 if str(weight_quantize_type).startswith(
            "channel_wise") else None
        out = {}
        for k, v in state.items():
            arr = unwrap(v) if hasattr(v, "_data") else jnp.asarray(v)
            if arr.ndim >= 2:
                q, scale = quantize_abs_max(
                    Tensor(arr, _internal=True), bits=weight_bits,
                    channel_axis=channel_axis)
                out[k] = (q, scale)
            else:
                out[k] = arr
        return out


def extend_with_decoupled_weight_decay(base_optimizer_cls):
    """ref: extend_optimizer_with_weight_decay.py: returns a subclass
    whose update applies decoupled (AdamW-style) weight decay."""

    class DecoupledWeightDecay(base_optimizer_cls):
        def __init__(self, *args, coeff=0.0, **kwargs):
            super().__init__(*args, **kwargs)
            self._coeff = coeff

        def _update(self, p, g, s, lr):
            new_p, ns = super()._update(p, g, s, lr)
            return new_p - lr * self._coeff * p, ns

    DecoupledWeightDecay.__name__ = \
        base_optimizer_cls.__name__ + "WithDecoupledWeightDecay"
    return DecoupledWeightDecay


def distributed_batch_reader(batch_reader):
    """Shard a batch reader by trainer rank (ref:
    reader/distributed_reader.py)."""

    def impl():
        from ..dist import env as denv

        rank = denv.get_rank() if hasattr(denv, "get_rank") else 0
        world = denv.get_world_size() if hasattr(denv, "get_world_size") \
            else 1
        for i, batch in enumerate(batch_reader()):
            if i % world == rank:
                yield batch

    return impl


def memory_usage(program, batch_size=1):
    """Rough activation+param memory of a Program in MB (ref:
    memory_usage_calc.py)."""
    total = 0
    for block in getattr(program, "blocks", []):
        for var in getattr(block, "vars", {}).values():
            shape = getattr(var, "shape", None)
            if not shape:
                continue
            n = 1
            for s in shape:
                n *= batch_size if s in (-1, None) else int(s)
            total += n * 4
    return total / 1024.0 / 1024.0


def op_freq_statistic(program):
    """Count ops by type in a Program (ref: op_frequence.py)."""
    uni, counts = {}, {}
    for block in getattr(program, "blocks", []):
        for op in getattr(block, "ops", []):
            t = getattr(op, "type", str(op))
            counts[t] = counts.get(t, 0) + 1
            uni.setdefault(t, 0)
            uni[t] += 1
    return uni, counts


# fluid.contrib.slim namespace (ref: fluid/contrib/slim/): pruning +
# distillation live in paddle_tpu.slim; quantization in paddle_tpu.quant
from .. import slim  # noqa: E402,F401


# contrib analysis tools (ref: fluid/contrib/memory_usage_calc.py:46,
# model_stat.py:40, op_frequence.py) — implementations in utils/stats.py
# read the compiled executable's own memory/cost analysis
from ..utils.stats import memory_usage, summary as model_summary  # noqa: E402,F401
import types as _types  # noqa: E402

memory_usage_calc = _types.SimpleNamespace(memory_usage=memory_usage)
model_stat = _types.SimpleNamespace(summary=model_summary)
op_frequence = _types.SimpleNamespace(op_freq_statistic=op_freq_statistic)

# fluid.contrib.utils (hdfs + lookup-table utils): real submodule
# registered under the dotted name so `from paddle_tpu.fluid.contrib
# import utils` and `import paddle_tpu.fluid.contrib.utils` both work
# even though contrib is a flat module (ref: fluid/contrib/utils/)
import sys as _sys  # noqa: E402

from . import contrib_utils as utils  # noqa: E402,F401

_sys.modules[__name__ + ".utils"] = utils
