"""fluid-era recurrent API compat.

Refs: python/paddle/fluid/layers/rnn.py — dynamic_lstm (:1861), lstm
(:2018), dynamic_lstmp (:2193), dynamic_gru (:2396), gru_unit (:2549),
lstm_unit (:2922), DecodeHelper family (:1272-1725), BasicDecoder
(:1726), beam_search_decode (:2849); layers/control_flow.py StaticRNN,
layers/rnn.py DynamicRNN.

TPU design notes:
- All sequence ops run dense (batch, time, feature) with optional
  ``sequence_length`` masking — the dense+offsets LoD stand-in used
  across ``ops/sequence.py`` (multi-level LoD is descoped, SURVEY §4b).
- Recurrences compile to ONE ``lax.scan`` per call via
  ``nn.layers.rnn.rnn`` — not per-step op launches.
- ``StaticRNN``/``DynamicRNN`` accept the step as a callable: the
  fluid with-block sugar builds a sub-block program, which an eager
  tape can't re-execute per step; the callable form is the same
  contract with the block made explicit.
"""
from __future__ import annotations

import math

import numpy as np

from .. import ops as _ops
from ..core.tensor import Tensor
from ..inference.decoder import Decoder, dynamic_decode  # noqa: F401
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from ..nn.layers.rnn import (RNNCellBase, LSTM as _LSTM, rnn as _rnn_run)

__all__ = [
    "RNNCell", "StaticRNN", "DynamicRNN", "dynamic_lstm", "dynamic_lstmp",
    "dynamic_gru", "gru_unit", "lstm_unit", "lstm", "DecodeHelper",
    "TrainingHelper", "GreedyEmbeddingHelper", "SampleEmbeddingHelper",
    "BasicDecoder", "beam_search_decode", "gather_tree",
]

RNNCell = RNNCellBase  # fluid name for the cell protocol


def _act(name):
    return {"sigmoid": F.sigmoid, "tanh": F.tanh, "relu": F.relu,
            "identity": (lambda x: x)}[name]


# -- fluid LSTM/GRU sequence ops --------------------------------------------


class _FluidLSTMCell(RNNCellBase):
    """Recurrent-only LSTM cell over pre-projected inputs: x already
    carries W_x·x (ref dynamic_lstm contract). Gate order c,i,f,o;
    optional peephole weights appended to the bias."""

    def __init__(self, hidden, param_attr, bias_attr, use_peepholes,
                 gate_act, cell_act, cand_act):
        super().__init__()
        std = 1.0 / math.sqrt(hidden)
        u = I.Uniform(-std, std)
        self.weight = self.create_parameter((hidden, 4 * hidden),
                                            attr=param_attr,
                                            default_initializer=u)
        nb = 7 * hidden if use_peepholes else 4 * hidden
        self.bias = self.create_parameter((nb,), attr=bias_attr,
                                          is_bias=True)
        self.hidden = hidden
        self.use_peepholes = use_peepholes
        self.gate_act, self.cell_act, self.cand_act = gate_act, cell_act, \
            cand_act

    @property
    def state_shape(self):
        return ((self.hidden,), (self.hidden,))

    def forward(self, x, states):
        h, c = states
        H = self.hidden
        g = x + _ops.matmul(h, self.weight) + self.bias[:4 * H]
        gc, gi, gf, go = (g[:, :H], g[:, H:2 * H], g[:, 2 * H:3 * H],
                          g[:, 3 * H:])
        act_g, act_c, act_d = (_act(self.gate_act), _act(self.cell_act),
                               _act(self.cand_act))
        if self.use_peepholes:
            w_ic = self.bias[4 * H:5 * H]
            w_fc = self.bias[5 * H:6 * H]
            w_oc = self.bias[6 * H:]
            i = act_g(gi + w_ic * c)
            f = act_g(gf + w_fc * c)
            new_c = f * c + i * act_d(gc)
            o = act_g(go + w_oc * new_c)
        else:
            i, f, o = act_g(gi), act_g(gf), act_g(go)
            new_c = f * c + i * act_d(gc)
        new_h = o * act_c(new_c)
        return new_h, (new_h, new_c)


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None,
                 sequence_length=None):
    """LSTM over a pre-projected sequence (ref: rnn.py:1861). ``input``
    is (B, T, 4*hidden); returns (hidden_seq, cell_seq)."""
    hidden = size // 4
    cell = _FluidLSTMCell(hidden, param_attr, bias_attr, use_peepholes,
                          gate_activation, cell_activation,
                          candidate_activation)
    init = None
    if h_0 is not None:
        init = (h_0, c_0 if c_0 is not None else _ops.zeros_like(h_0))
    return _rnn_with_cell_states(cell, input, init, sequence_length,
                                 is_reverse)


def _rnn_with_cell_states(cell, input, init, sequence_length, is_reverse):
    """Run an (h, c)-state cell returning both per-step h and c. The
    first state's width (projection size for LSTMP, hidden otherwise)
    comes from the cell's state_shape."""
    split = int(cell.state_shape[0][0])

    class _Both(Layer):
        def __init__(self, c):
            super().__init__()
            self.c = c

        def get_initial_states(self, *a, **k):
            return self.c.get_initial_states(*a, **k)

        @property
        def state_shape(self):
            return self.c.state_shape

        def forward(self, x, states):
            h, st = self.c(x, states)
            return _ops.concat([h, st[1]], axis=-1), st

    ys, _ = _rnn_run(_Both(cell), input, init, sequence_length,
                     is_reverse=is_reverse)
    ys = Tensor(ys, _internal=True) if not isinstance(ys, Tensor) else ys
    return ys[:, :, :split], ys[:, :, split:]


class _FluidLSTMPCell(RNNCellBase):
    """LSTM with a projection of the hidden state (ref dynamic_lstmp,
    rnn.py:2193): recurrence runs over r_t = act_p(h_t · W_proj)."""

    def __init__(self, hidden, proj, param_attr, bias_attr, use_peepholes,
                 gate_act, cell_act, cand_act, proj_act):
        super().__init__()
        self.weight = self.create_parameter((proj, 4 * hidden),
                                            attr=param_attr)
        self.w_proj = self.create_parameter((hidden, proj), attr=param_attr)
        nb = 7 * hidden if use_peepholes else 4 * hidden
        self.bias = self.create_parameter((nb,), attr=bias_attr,
                                          is_bias=True)
        self.hidden, self.proj = hidden, proj
        self.use_peepholes = use_peepholes
        self.gate_act, self.cell_act = gate_act, cell_act
        self.cand_act, self.proj_act = cand_act, proj_act

    @property
    def state_shape(self):
        return ((self.proj,), (self.hidden,))

    def forward(self, x, states):
        r, c = states
        H = self.hidden
        g = x + _ops.matmul(r, self.weight) + self.bias[:4 * H]
        gc, gi, gf, go = (g[:, :H], g[:, H:2 * H], g[:, 2 * H:3 * H],
                          g[:, 3 * H:])
        act_g, act_c = _act(self.gate_act), _act(self.cell_act)
        act_d, act_p = _act(self.cand_act), _act(self.proj_act)
        if self.use_peepholes:
            i = act_g(gi + self.bias[4 * H:5 * H] * c)
            f = act_g(gf + self.bias[5 * H:6 * H] * c)
            new_c = f * c + i * act_d(gc)
            o = act_g(go + self.bias[6 * H:] * new_c)
        else:
            i, f, o = act_g(gi), act_g(gf), act_g(go)
            new_c = f * c + i * act_d(gc)
        new_h = o * act_c(new_c)
        new_r = act_p(_ops.matmul(new_h, self.w_proj))
        return new_r, (new_r, new_c)


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None, h_0=None, c_0=None,
                  cell_clip=None, proj_clip=None, sequence_length=None):
    """Projected LSTM (ref: rnn.py:2193). input: (B, T, 4*hidden);
    returns (projection_seq, cell_seq)."""
    hidden = size // 4
    cell = _FluidLSTMPCell(hidden, proj_size, param_attr, bias_attr,
                           use_peepholes, gate_activation, cell_activation,
                           candidate_activation, proj_activation)
    init = None
    if h_0 is not None:
        if c_0 is None:
            B = h_0.shape[0]
            c_0 = _ops.zeros([B, hidden], dtype="float32")
        init = (h_0, c_0)
    return _rnn_with_cell_states(cell, input, init, sequence_length,
                                 is_reverse)


class _FluidGRUCell(RNNCellBase):
    """GRU over pre-projected inputs (ref dynamic_gru, rnn.py:2396).
    Weight (D, 3D): [W_uh | W_rh | W_ch]; gates u, r then candidate."""

    def __init__(self, hidden, param_attr, bias_attr, gate_act, cand_act,
                 origin_mode):
        super().__init__()
        std = 1.0 / math.sqrt(hidden)
        u = I.Uniform(-std, std)
        self.weight = self.create_parameter((hidden, 3 * hidden),
                                            attr=param_attr,
                                            default_initializer=u)
        self.bias = self.create_parameter((3 * hidden,), attr=bias_attr,
                                          is_bias=True)
        self.hidden = hidden
        self.gate_act, self.cand_act = gate_act, cand_act
        self.origin_mode = origin_mode

    @property
    def state_shape(self):
        return (self.hidden,)

    def forward(self, x, states):
        h = states
        H = self.hidden
        xb = x + self.bias
        gates = xb[:, :2 * H] + _ops.matmul(h, self.weight[:, :2 * H])
        act_g, act_c = _act(self.gate_act), _act(self.cand_act)
        u = act_g(gates[:, :H])
        r = act_g(gates[:, H:])
        c = act_c(xb[:, 2 * H:] + _ops.matmul(r * h, self.weight[:, 2 * H:]))
        if self.origin_mode:
            new_h = u * h + (1.0 - u) * c
        else:
            new_h = (1.0 - u) * h + u * c
        return new_h, new_h


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False,
                sequence_length=None):
    """GRU over a pre-projected (B, T, 3*size) sequence (ref:
    rnn.py:2396); returns the hidden sequence (B, T, size)."""
    cell = _FluidGRUCell(size, param_attr, bias_attr, gate_activation,
                         candidate_activation, origin_mode)
    ys, _ = _rnn_run(cell, input, h_0, sequence_length,
                     is_reverse=is_reverse)
    return Tensor(ys, _internal=True) if not isinstance(ys, Tensor) else ys


def _gru_step(cell, input, hidden, gate_activation, activation,
              origin_mode):
    """Single fused GRU step over a _FluidGRUCell's weights — shared by
    gru_unit and fluid.dygraph.GRUUnit so the gate math lives once."""
    D = cell.hidden
    xb = input + cell.bias
    gates = xb[:, :2 * D] + _ops.matmul(hidden, cell.weight[:, :2 * D])
    act_g, act_c = _act(gate_activation), _act(activation)
    u = act_g(gates[:, :D])
    r = act_g(gates[:, D:])
    r_h = r * hidden
    c = act_c(xb[:, 2 * D:] + _ops.matmul(r_h, cell.weight[:, 2 * D:]))
    if origin_mode:
        new_h = u * hidden + (1.0 - u) * c
    else:
        new_h = (1.0 - u) * hidden + u * c
    return new_h, r_h, _ops.concat([u, r, c], axis=-1)


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """One GRU step (ref: rnn.py:2549). ``size`` is 3*D as in fluid.
    Returns (new_hidden, reset_hidden_prev, gate)."""
    cell = _FluidGRUCell(size // 3, param_attr, bias_attr, gate_activation,
                         activation, origin_mode)
    return _gru_step(cell, input, hidden, gate_activation, activation,
                     origin_mode)


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """One fused LSTM step over concat([x, h]) (ref: rnn.py:2922).
    Returns (hidden, cell)."""
    H = hidden_t_prev.shape[-1]
    concat = _ops.concat([x_t, hidden_t_prev], axis=-1)
    from .layers import fc

    g = fc(concat, 4 * H, param_attr=param_attr, bias_attr=bias_attr)
    i, f, c_cand, o = (g[:, :H], g[:, H:2 * H], g[:, 2 * H:3 * H],
                       g[:, 3 * H:])
    new_c = F.sigmoid(f + forget_bias) * cell_t_prev + \
        F.sigmoid(i) * F.tanh(c_cand)
    new_h = F.sigmoid(o) * F.tanh(new_c)
    return new_h, new_c


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """cuDNN-style stacked LSTM (ref: rnn.py:2018) on the framework's
    fused-scan LSTM. input: (B, T, D); init_h/init_c: (L*dirs, B, H).
    Returns (out_seq, last_h, last_c)."""
    net = _LSTM(input.shape[-1], hidden_size, num_layers=num_layers,
                direction="bidirect" if is_bidirec else "forward",
                dropout=0.0 if is_test else dropout_prob)
    out, (h, c) = net(input, (init_h, init_c))
    return out, h, c


# -- StaticRNN / DynamicRNN --------------------------------------------------


class StaticRNN:
    """Unrolled recurrence over fixed-length sequences (ref:
    control_flow.py StaticRNN). The per-step block is a callable::

        srnn = StaticRNN()
        srnn.step_input(x)                 # (B, T, D) sequence
        srnn.memory(init=h0)               # recurrent state
        srnn.step(lambda xt, h: (out, h')) # block
        outs = srnn()                      # (B, T, ...) stacked outputs

    The step callable receives one tensor per registered step_input then
    one per memory, and returns (output, *new_memories).
    """

    def __init__(self, name=None):
        self._inputs = []
        self._mems = []
        self._fn = None

    def step_input(self, x):
        self._inputs.append(x)
        return x

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1):
        if init is None:
            ref = batch_ref if batch_ref is not None else self._inputs[0]
            B = ref.shape[0]
            init = _ops.full([B] + list(shape), init_value)
        self._mems.append(init)
        return init

    def step(self, fn):
        self._fn = fn
        return fn

    def __call__(self):
        assert self._fn is not None and self._inputs, \
            "register step_input() and a step() callable first"
        T = self._inputs[0].shape[1]
        mems = list(self._mems)
        outs = []
        for t in range(T):
            xs = [x[:, t] for x in self._inputs]
            res = self._fn(*xs, *mems)
            if not isinstance(res, tuple):
                res = (res,)
            out, new_mems = res[0], list(res[1:])
            mems = new_mems if new_mems else mems
            outs.append(out)
        return _ops.stack(outs, axis=1)


class DynamicRNN(StaticRNN):
    """Variable-length recurrence (ref: rnn.py DynamicRNN): same step
    contract as StaticRNN plus per-row ``sequence_length`` masking —
    finished rows keep their last state and emit zeros."""

    def __init__(self, name=None):
        super().__init__(name)
        self._lengths = None

    def step_input(self, x, lengths=None):
        if lengths is not None:
            self._lengths = lengths
        return super().step_input(x)

    def __call__(self):
        assert self._fn is not None and self._inputs
        T = self._inputs[0].shape[1]
        mems = list(self._mems)
        outs = []
        for t in range(T):
            xs = [x[:, t] for x in self._inputs]
            res = self._fn(*xs, *mems)
            if not isinstance(res, tuple):
                res = (res,)
            out, new_mems = res[0], list(res[1:])
            if self._lengths is not None:
                alive = (self._lengths > t)
                keep = _ops.reshape(alive, [-1] + [1] * (len(out.shape) - 1))
                out = _ops.where(keep, out, _ops.zeros_like(out))
                if new_mems:
                    new_mems = [
                        _ops.where(_ops.reshape(
                            alive, [-1] + [1] * (len(n.shape) - 1)), n, m)
                        for n, m in zip(new_mems, mems)]
            mems = new_mems if new_mems else mems
            outs.append(out)
        return _ops.stack(outs, axis=1)


# -- decode helpers ----------------------------------------------------------


class DecodeHelper:
    """Sampling + next-input protocol for BasicDecoder (ref:
    rnn.py:1272)."""

    def initialize(self):
        """-> (initial_inputs, initial_finished)"""
        raise NotImplementedError

    def sample(self, time, outputs, states):
        raise NotImplementedError

    def next_inputs(self, time, outputs, states, sample_ids):
        """-> (finished, next_inputs, next_states)"""
        raise NotImplementedError


class TrainingHelper(DecodeHelper):
    """Teacher forcing from a ground-truth sequence (ref: rnn.py:1341)."""

    def __init__(self, inputs, sequence_length, time_major=False):
        self.inputs = inputs if not time_major else _ops.transpose(
            inputs, [1, 0] + list(range(2, len(inputs.shape))))
        self.sequence_length = sequence_length

    def initialize(self):
        finished = (self.sequence_length <= 0)
        return self.inputs[:, 0], finished

    def sample(self, time, outputs, states):
        return _ops.argmax(outputs, axis=-1)

    def next_inputs(self, time, outputs, states, sample_ids):
        T = self.inputs.shape[1]
        nt = min(time + 1, T - 1)
        finished = (self.sequence_length <= (time + 1))
        return finished, self.inputs[:, nt], states


class GreedyEmbeddingHelper(DecodeHelper):
    """Argmax then embed (ref: rnn.py:1494)."""

    def __init__(self, embedding_fn, start_tokens, end_token):
        self.embedding_fn = embedding_fn
        self.start_tokens = start_tokens
        self.end_token = int(end_token)

    def initialize(self):
        finished = _ops.zeros_like(self.start_tokens).astype("bool")
        return self.embedding_fn(self.start_tokens), finished

    def sample(self, time, outputs, states):
        return _ops.argmax(outputs, axis=-1)

    def next_inputs(self, time, outputs, states, sample_ids):
        finished = _ops.equal(
            sample_ids, _ops.full_like(sample_ids, self.end_token))
        return finished, self.embedding_fn(sample_ids), states


class SampleEmbeddingHelper(GreedyEmbeddingHelper):
    """Multinomial sampling then embed (ref: rnn.py:1625)."""

    def __init__(self, embedding_fn, start_tokens, end_token,
                 softmax_temperature=None, seed=None):
        super().__init__(embedding_fn, start_tokens, end_token)
        self.temperature = softmax_temperature

    def sample(self, time, outputs, states):
        logits = outputs if self.temperature is None else \
            outputs / self.temperature
        from ..distribution import Categorical

        return Categorical(logits=logits).sample([]).astype("int64")


class BasicDecoder(Decoder):
    """cell + helper -> Decoder for dynamic_decode (ref: rnn.py:1726).
    Step outputs are (cell_outputs, sample_ids) pairs."""

    def __init__(self, cell, helper, output_fn=None):
        self.cell = cell
        self.helper = helper
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        inputs, finished = self.helper.initialize()
        return inputs, initial_cell_states, finished

    def step(self, time, inputs, states):
        out, next_states = self.cell(inputs, states)
        if self.output_fn is not None:
            out = self.output_fn(out)
        sample_ids = self.helper.sample(time, out, next_states)
        finished, next_inputs, next_states = self.helper.next_inputs(
            time, out, next_states, sample_ids)
        return {"cell_outputs": out, "sample_ids": sample_ids}, \
            next_states, next_inputs, finished

    def finalize(self, outputs, final_states, sequence_lengths):
        stacked = {
            "cell_outputs": _ops.stack([o["cell_outputs"] for o in outputs],
                                       axis=1),
            "sample_ids": _ops.stack([o["sample_ids"] for o in outputs],
                                     axis=1),
        }
        return stacked, final_states


# -- beam search decode (gather tree) ---------------------------------------

from ..ops.misc import gather_tree  # noqa: E402  (fluid re-export)


def beam_search_decode(ids, parents, beam_size=None, end_id=None, name=None,
                       scores=None):
    """Full-sequence decode from per-step beam ids + parent pointers
    (ref: rnn.py:2849 beam_search_decode). The fluid op reads parent
    links out of the ids TensorArray's LoD; the dense+offsets design
    (SURVEY §4b) passes them explicitly: ``ids``/``parents`` are
    (T, B, K). Returns (sequences (T, B, K), scores or None — parent
    pointers are never a score stand-in)."""
    seqs = gather_tree(ids, parents)
    return seqs, scores
