"""fluid.incubate compatibility surface.

Refs: python/paddle/fluid/incubate/ —
- fleet/base/role_maker.py: Role, RoleMakerBase, UserDefinedRoleMaker,
  UserDefinedCollectiveRoleMaker, PaddleCloudRoleMaker (env-driven)
- fleet/base/fleet_base.py worker/server introspection + split_files
- data_generator/__init__.py: MultiSlotDataGenerator,
  MultiSlotStringDataGenerator (the CTR text-protocol generators)
- fleet/utils/utils.py: save_program/load_program

The parameter-server fleet mode itself is descoped (SURVEY §4b):
role makers exist so PS-era launch scripts can still introspect
rank/world and route into collective mode.
"""
from __future__ import annotations

import os
import sys

__all__ = [
    "Role", "RoleMakerBase", "UserDefinedRoleMaker",
    "UserDefinedCollectiveRoleMaker", "PaddleCloudRoleMaker",
    "MultiSlotDataGenerator", "MultiSlotStringDataGenerator",
    "split_files", "save_program", "load_program", "fleet",
]

from ..dist.fleet import fleet  # noqa: F401,E402


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    """ref: role_maker.py RoleMakerBase."""

    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_endpoints = []
        self._server_endpoints = []

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def worker_num(self):
        return len(self._worker_endpoints) or 1

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints

    def generate_role(self):
        pass

    def barrier_worker(self):
        """Collective barrier over the mesh (dist.collective.barrier)."""
        from ..dist import env as denv

        if denv.get_world_size() <= 1:
            return
        from ..dist.collective import barrier

        barrier()


class UserDefinedRoleMaker(RoleMakerBase):
    """ref: role_maker.py UserDefinedRoleMaker."""

    def __init__(self, current_id=0, role=Role.WORKER, worker_num=0,
                 server_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_endpoints = ["127.0.0.1:0"] * worker_num
        self._server_endpoints = list(server_endpoints or [])


class UserDefinedCollectiveRoleMaker(RoleMakerBase):
    """ref: role_maker.py UserDefinedCollectiveRoleMaker."""

    def __init__(self, current_id=0, worker_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._worker_endpoints = list(worker_endpoints or ["127.0.0.1:0"])


class PaddleCloudRoleMaker(RoleMakerBase):
    """ref: role_maker.py PaddleCloudRoleMaker: rank/world from the
    launch environment (here: the jax distributed env)."""

    def __init__(self, is_collective=True):
        super().__init__()
        from ..dist import env as denv

        self._current_id = int(os.environ.get(
            "PADDLE_TRAINER_ID", denv.get_rank()))
        n = int(os.environ.get("PADDLE_TRAINERS_NUM",
                               denv.get_world_size()))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS")
        self._worker_endpoints = eps.split(",") if eps \
            else ["127.0.0.1:0"] * n


def split_files(files, trainer_id=None, trainers=None):
    """Shard a file list across workers (ref: fleet_base.py
    split_files)."""
    from ..dist import env as denv

    trainer_id = denv.get_rank() if trainer_id is None else trainer_id
    trainers = denv.get_world_size() if trainers is None else trainers
    return [f for i, f in enumerate(sorted(files))
            if i % trainers == trainer_id]


class MultiSlotDataGenerator:
    """ref: data_generator/__init__.py MultiSlotDataGenerator — the CTR
    slot-data text protocol: each sample is [(slot_name, [values])...]
    serialized per slot as "<n> v1 .. vn" (names are schema, not wire
    data). Subclasses override generate_sample(line) returning an
    iterator of samples; generate_batch may be overridden to transform
    each sample stream before serialization."""

    def __init__(self):
        self._proto_info = None

    def generate_sample(self, line):
        raise NotImplementedError

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s

        return local_iter

    def _format(self, sample):
        parts = []
        for name, values in sample:
            parts.append(str(len(values)))
            parts += [str(v) for v in values]
        return " ".join(parts)

    def run_from_memory(self, lines=("",)):
        """Yield serialized sample lines (test/dev path)."""
        for line in lines:
            it = self.generate_sample(line)
            for sample in self.generate_batch(list(it()))():
                yield self._format(sample)

    def run_from_stdin(self):
        for line in sys.stdin:
            it = self.generate_sample(line)
            for sample in self.generate_batch(list(it()))():
                sys.stdout.write(self._format(sample) + "\n")


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    """String-valued slots (ref: MultiSlotStringDataGenerator)."""


def save_program(program, model_filename):
    """Serialize a Program's symbolic description (ref:
    fleet/utils/utils.py save_program)."""
    with open(model_filename, "w") as f:
        f.write(program.to_string() if hasattr(program, "to_string")
                else str(program))


def load_program(model_filename, is_text=True):
    """Load a saved Program DESCRIPTION (text, for inspection — the
    reference pairs these utils with PS-mode debugging). The executable
    round-trip is save_inference_model/load_inference_model; binary
    protos don't exist here, so is_text=False raises."""
    if not is_text:
        raise NotImplementedError(
            "binary program protos are fluid-era; use "
            "save_inference_model/load_inference_model for an "
            "executable round-trip")
    with open(model_filename) as f:
        return f.read()
