"""fluid.incubate compatibility surface.

Refs: python/paddle/fluid/incubate/ —
- fleet/base/role_maker.py: Role, RoleMakerBase, UserDefinedRoleMaker,
  UserDefinedCollectiveRoleMaker, PaddleCloudRoleMaker (env-driven)
- fleet/base/fleet_base.py worker/server introspection + split_files
- data_generator/__init__.py: MultiSlotDataGenerator,
  MultiSlotStringDataGenerator (the CTR text-protocol generators)
- fleet/utils/utils.py: save_program/load_program

The parameter-server fleet mode itself is descoped (SURVEY §4b):
role makers exist so PS-era launch scripts can still introspect
rank/world and route into collective mode.
"""
from __future__ import annotations

import os
import sys

__all__ = [
    "Role", "RoleMakerBase", "UserDefinedRoleMaker",
    "UserDefinedCollectiveRoleMaker", "PaddleCloudRoleMaker",
    "MultiSlotDataGenerator", "MultiSlotStringDataGenerator",
    "split_files", "save_program", "load_program", "fleet",
]

from ..dist.fleet import fleet  # noqa: F401,E402


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    """ref: role_maker.py RoleMakerBase."""

    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_endpoints = []
        self._server_endpoints = []

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def worker_num(self):
        return len(self._worker_endpoints) or 1

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints

    def generate_role(self):
        pass

    def barrier_worker(self):
        """Collective barrier over the mesh (dist.collective.barrier)."""
        from ..dist import env as denv

        if denv.get_world_size() <= 1:
            return
        from ..dist.collective import barrier

        barrier()


class UserDefinedRoleMaker(RoleMakerBase):
    """ref: role_maker.py UserDefinedRoleMaker."""

    def __init__(self, current_id=0, role=Role.WORKER, worker_num=0,
                 server_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_endpoints = ["127.0.0.1:0"] * worker_num
        self._server_endpoints = list(server_endpoints or [])


class UserDefinedCollectiveRoleMaker(RoleMakerBase):
    """ref: role_maker.py UserDefinedCollectiveRoleMaker."""

    def __init__(self, current_id=0, worker_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._worker_endpoints = list(worker_endpoints or ["127.0.0.1:0"])


class PaddleCloudRoleMaker(RoleMakerBase):
    """ref: role_maker.py PaddleCloudRoleMaker: rank/world from the
    launch environment (here: the jax distributed env)."""

    def __init__(self, is_collective=True):
        super().__init__()
        from ..dist import env as denv

        self._current_id = int(os.environ.get(
            "PADDLE_TRAINER_ID", denv.get_rank()))
        n = int(os.environ.get("PADDLE_TRAINERS_NUM",
                               denv.get_world_size()))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS")
        self._worker_endpoints = eps.split(",") if eps \
            else ["127.0.0.1:0"] * n


def split_files(files, trainer_id=None, trainers=None):
    """Shard a file list across workers (ref: fleet_base.py
    split_files)."""
    from ..dist import env as denv

    trainer_id = denv.get_rank() if trainer_id is None else trainer_id
    trainers = denv.get_world_size() if trainers is None else trainers
    return [f for i, f in enumerate(sorted(files))
            if i % trainers == trainer_id]


class MultiSlotDataGenerator:
    """ref: data_generator/__init__.py MultiSlotDataGenerator — the CTR
    slot-data text protocol: each sample is [(slot_name, [values])...]
    serialized per slot as "<n> v1 .. vn" (names are schema, not wire
    data). Subclasses override generate_sample(line) returning an
    iterator of samples; generate_batch may be overridden to transform
    each sample stream before serialization."""

    def __init__(self):
        self._proto_info = None

    def generate_sample(self, line):
        raise NotImplementedError

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s

        return local_iter

    def _format(self, sample):
        parts = []
        for name, values in sample:
            parts.append(str(len(values)))
            parts += [str(v) for v in values]
        return " ".join(parts)

    def run_from_memory(self, lines=("",)):
        """Yield serialized sample lines (test/dev path)."""
        for line in lines:
            it = self.generate_sample(line)
            for sample in self.generate_batch(list(it()))():
                yield self._format(sample)

    def run_from_stdin(self):
        for line in sys.stdin:
            it = self.generate_sample(line)
            for sample in self.generate_batch(list(it()))():
                sys.stdout.write(self._format(sample) + "\n")


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    """String-valued slots (ref: MultiSlotStringDataGenerator)."""


def save_program(program, model_filename):
    """Serialize a Program's symbolic description (ref:
    fleet/utils/utils.py save_program)."""
    with open(model_filename, "w") as f:
        f.write(program.to_string() if hasattr(program, "to_string")
                else str(program))


def load_program(model_filename, is_text=True):
    """Load a saved Program DESCRIPTION (text, for inspection — the
    reference pairs these utils with PS-mode debugging). The executable
    round-trip is save_inference_model/load_inference_model; binary
    protos don't exist here, so is_text=False raises."""
    if not is_text:
        raise NotImplementedError(
            "binary program protos are fluid-era; use "
            "save_inference_model/load_inference_model for an "
            "executable round-trip")
    with open(model_filename) as f:
        return f.read()


class MPISymetricRoleMaker(RoleMakerBase):
    """ref: role_maker.py:225 — MPI rank-symmetric roles (every process
    is both worker and server in the reference's PS clusters). On the
    TPU single-controller SPMD design there are no server processes, so
    every rank is a worker; rank/size come from the jax distributed env
    (the role MPI_COMM_WORLD plays in the reference).
    """

    def __init__(self):
        super().__init__()
        import jax

        # process-level roles: single-controller SPMD means one worker
        # per HOST process (devices are not workers), matching the role
        # MPI ranks play in the reference
        self._current_id = jax.process_index()
        n = jax.process_count()
        self._worker_endpoints = ["127.0.0.1:0"] * n
        self._generated = False

    def generate_role(self):
        self._generated = True

    def _check_role_generation(self):
        if not self._generated:
            raise RuntimeError("call generate_role() first")
        return True

    def all_gather(self, input):
        """Gather a host value from every worker process. With one
        process this is just the singleton list; multi-host gathers ride
        a device collective on a scalar."""
        import jax

        if jax.process_count() <= 1:
            return [input]
        from ..dist.collective import all_gather as _ag

        import numpy as np

        return list(np.asarray(_ag(np.asarray(input))))

    def all_reduce_worker(self, input, output=None, mode="sum"):
        import jax

        if jax.process_count() <= 1:
            return input
        from ..dist.collective import ReduceOp, all_reduce

        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX,
              "min": ReduceOp.MIN}[mode]
        return all_reduce(input, op=op)

    def barrier_all(self):
        self.barrier_worker()


class GeneralRoleMaker(RoleMakerBase):
    """ref: role_maker.py GeneralRoleMaker — env-driven roles with an
    http/gloo barrier server. Rank/size resolve exactly like
    PaddleCloudRoleMaker; barriers ride the mesh collective."""

    def __init__(self, **kwargs):
        super().__init__()
        from ..dist import env as denv

        self._current_id = int(os.environ.get(
            "PADDLE_TRAINER_ID", denv.get_rank()))
        n = int(os.environ.get("PADDLE_TRAINERS_NUM",
                               denv.get_world_size()))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS")
        self._worker_endpoints = eps.split(",") if eps \
            else ["127.0.0.1:0"] * n
        self._kwargs = kwargs

    def generate_role(self):
        pass

    def barrier_all(self):
        self.barrier_worker()


# -- parameter-server DistributedStrategy configs ---------------------------
# (ref: incubate/fleet/parameter_server/distribute_transpiler/
# distributed_strategy.py). The config classes are real and validate;
# the PS *runtime* they would configure stays the recorded §4b descope —
# StrategyFactory maps each mode onto the collective-mode equivalent.


class TrainerRuntimeConfig:
    """ref: distributed_strategy.py:25 — async-communicator knobs."""

    def __init__(self):
        self.max_merge_var_num = 20
        self.send_queue_size = 20
        self.independent_recv_thread = True
        self.min_send_grad_num_before_recv = 20
        self.thread_pool_size = 5
        self.send_wait_times = 5

    def get_communicator_flags(self):
        return {"communicator_" + k: v for k, v in vars(self).items()}

    def display(self, configs):
        lines = [f"{k}: {v}" for k, v in sorted(configs.items())]
        return "\n".join(lines)

    def __repr__(self):
        return self.display(self.get_communicator_flags())


class PSDistributedStrategy:
    """ref: distributed_strategy.py:127 DistributedStrategy (the PS one —
    distinct from dist.fleet.DistributedStrategy, which is the collective
    strategy this maps onto)."""

    def __init__(self):
        self._program_config = {"sync_mode": True, "runtime_split_send_recv":
                                False, "geo_sgd_mode": False}
        self._trainer_runtime_config = TrainerRuntimeConfig()
        self._server_runtime_config = {}
        self._execute_strategy = None
        self._build_strategy = None
        self._debug_opt = None

    def set_debug_opt(self, opt_info):
        self._debug_opt = opt_info

    def get_debug_opt(self):
        return dict(self._debug_opt or {})

    def get_program_config(self):
        return self._program_config

    def set_program_config(self, config):
        if isinstance(config, dict):
            bad = set(config) - set(self._program_config)
            if bad:
                raise ValueError(f"unknown program_config keys {sorted(bad)}")
            self._program_config.update(config)
        else:
            self._program_config = config

    def get_trainer_runtime_config(self):
        return self._trainer_runtime_config

    def set_trainer_runtime_config(self, config):
        if isinstance(config, dict):
            for k, v in config.items():
                if not hasattr(self._trainer_runtime_config, k):
                    raise ValueError(f"unknown runtime config {k}")
                setattr(self._trainer_runtime_config, k, v)
        else:
            self._trainer_runtime_config = config

    def get_server_runtime_config(self):
        return self._server_runtime_config

    def set_server_runtime_config(self, config):
        self._server_runtime_config = config

    def get_execute_strategy(self):
        return self._execute_strategy

    def set_execute_strategy(self, config):
        self._execute_strategy = config

    def get_build_strategy(self):
        return self._build_strategy

    def set_build_strategy(self, config):
        self._build_strategy = config

    def to_collective(self):
        """The TPU mapping: every PS mode runs as collective DP."""
        from ..dist.fleet import DistributedStrategy as _CS

        return _CS()


class SyncStrategy(PSDistributedStrategy):
    def __init__(self):
        super().__init__()
        self._program_config["sync_mode"] = True


class AsyncStrategy(PSDistributedStrategy):
    def __init__(self):
        super().__init__()
        self._program_config["sync_mode"] = False


class HalfAsyncStrategy(AsyncStrategy):
    pass


class GeoStrategy(PSDistributedStrategy):
    def __init__(self, update_frequency=100):
        super().__init__()
        self._program_config["sync_mode"] = False
        self._program_config["geo_sgd_mode"] = True
        self._program_config["geo_sgd_need_push_nums"] = update_frequency


class StrategyFactory:
    """ref: distributed_strategy.py StrategyFactory."""

    @staticmethod
    def create_sync_strategy():
        return SyncStrategy()

    @staticmethod
    def create_half_async_strategy():
        return HalfAsyncStrategy()

    @staticmethod
    def create_async_strategy():
        return AsyncStrategy()

    @staticmethod
    def create_geo_strategy(update_frequency=100):
        return GeoStrategy(update_frequency)


FLEET_GLOBAL_DICT = {
    # ref: pslib/optimizer_factory.py FLEET_GLOBAL_DICT — plumbing the
    # pslib op-rewrite passes share; kept for import compat
    "enable": False, "emb_to_table": {}, "emb_to_accessor": {},
    "emb_to_size": {}, "cur_sparse_id": 0, "cur_accessor": "",
    "click_name": "", "scale_sparse_grad": None,
}


class DistributedAdam:
    """ref: pslib/optimizer_factory.py DistributedAdam — rewrites the
    program for pslib sparse tables (recorded §4b descope). The TPU
    equivalent of distributed sparse embeddings is
    dist.tp_layers.VocabParallelEmbedding + a standard Adam."""

    def __init__(self, optimizer=None):
        self._optimizer = optimizer

    def minimize(self, *a, **k):
        raise NotImplementedError(
            "pslib sparse-table optimization is parameter-server "
            "machinery (SURVEY §4b descope); shard embeddings with "
            "VocabParallelEmbedding and use optim.Adam")


__all__ += ["MPISymetricRoleMaker", "GeneralRoleMaker",
            "TrainerRuntimeConfig", "PSDistributedStrategy", "SyncStrategy",
            "AsyncStrategy", "HalfAsyncStrategy", "GeoStrategy",
            "StrategyFactory", "DistributedAdam", "FLEET_GLOBAL_DICT"]


class CollectiveDistributedStrategy:
    """ref: incubate/fleet/collective/__init__.py:334 DistributedStrategy
    (the collective-mode one — extends BuildStrategy with collective
    knobs). XLA owns graph construction, so the knobs are config-only;
    collective_mode='grad_allreduce' is what the SPMD executor path
    implements, 'local_sgd' maps to it (see transpiler.LocalSGD)."""

    def __init__(self):
        from ..static_ import BuildStrategy, ExecutionStrategy

        self.build_strategy = BuildStrategy()
        self.use_local_sgd = False
        self.use_dist_fc = False
        self.dist_fc_config = None
        self.mode = "collective"
        self.collective_mode = "grad_allreduce"
        self.nccl_comm_num = 1
        self.forward_recompute = False
        self.recompute_checkpoints = []
        self.exec_strategy = ExecutionStrategy()


class CollectiveOptimizer:
    """ref: incubate/fleet/collective/__init__.py:382 — wraps an
    optimizer for collective (data-parallel) static training. The
    reference transpiles NCCL all-reduce ops into the program; here
    minimize() appends the standard backward+update ops and marks the
    program for the Executor's SPMD data-parallel path, which makes XLA
    insert the gradient all-reduce over ICI."""

    def __init__(self, optimizer, strategy=None):
        self._optimizer = optimizer
        self._strategy = strategy or CollectiveDistributedStrategy()

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        from ..static_.backward import append_backward

        return append_backward(loss, parameter_list=parameter_list)

    def apply_gradients(self, params_grads):
        from ..static_.executor import append_update_ops

        append_update_ops(self._optimizer, params_grads)
        return []

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ..static_.executor import build_optimize_ops
        from ..static_.program import default_main_program

        opt_ops, params_grads = build_optimize_ops(
            self._optimizer, loss, parameter_list=parameter_list)
        default_main_program()._transpiled_dp = True
        return opt_ops, params_grads


__all__ += ["CollectiveOptimizer", "CollectiveDistributedStrategy"]
