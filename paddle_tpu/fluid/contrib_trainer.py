"""fluid.contrib Trainer/Inferencer high-level API
(ref: python/paddle/fluid/contrib/trainer.py, inferencer.py — the 1.x
"high-level API" the book's high-level-api chapters drive).

Trainer owns the program pair + scope: ``train_func`` builds the graph
(loss first in its returns), ``optimizer_func`` supplies the optimizer,
and ``train`` runs the epoch/step event loop with Begin/End events,
periodic checkpointing (CheckpointConfig) and auto-resume from the
latest serial — all over the one-executable static Executor.
"""
from __future__ import annotations

import json
import os
import shutil

import numpy as np

from .. import static_ as _static
from ..static_ import Executor, Program, Scope, program_guard, scope_guard
from ..static_.program import global_scope  # noqa: F401 (re-export compat)

__all__ = ["Trainer", "Inferencer", "BeginEpochEvent", "EndEpochEvent",
           "BeginStepEvent", "EndStepEvent", "CheckpointConfig"]


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        #: set False in the handler to skip fetching metrics this step
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """ref: trainer.py:100 — where/how often to checkpoint."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10):
        assert epoch_interval >= 1
        assert step_interval >= 1
        self.checkpoint_dir = checkpoint_dir or os.getcwd()
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = epoch_interval
        self.step_interval = step_interval
        self.load_serial = None
        self.epoch_id = 0
        self.step_id = 0


def _serial_dir(cfg, serial):
    return os.path.join(cfg.checkpoint_dir, f"checkpoint_{serial}")


def _latest_serial(checkpoint_dir):
    best = -1
    if os.path.isdir(checkpoint_dir):
        for name in os.listdir(checkpoint_dir):
            if name.startswith("checkpoint_"):
                try:
                    best = max(best, int(name.split("_")[-1]))
                except ValueError:
                    pass
    return best


class _ModeGuard:
    """Enter static mode for a block, restoring the caller's mode."""

    def __enter__(self):
        self._was_static = _static.in_static_mode()
        if not self._was_static:
            _static.enable_static()
        return self

    def __exit__(self, *exc):
        if not self._was_static:
            _static.disable_static()


class Trainer:
    """ref: trainer.py:169."""

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None):
        self.__stop = False
        self.parallel = parallel
        self.trainer_id = 0
        self.checkpoint_cfg = checkpoint_config
        if self.checkpoint_cfg is not None:
            assert isinstance(self.checkpoint_cfg, CheckpointConfig)
            serial = _latest_serial(self.checkpoint_cfg.checkpoint_dir)
            self.checkpoint_cfg.load_serial = serial if serial >= 0 else None

        self.scope = Scope()
        self.startup_program = Program()
        self.train_program = Program()
        self.place = place

        from ..utils import unique_name

        with _ModeGuard(), scope_guard(self.scope), \
                program_guard(self.train_program, self.startup_program), \
                unique_name.guard():
            outs = train_func()
            self.train_func_outputs = outs if isinstance(outs, list) \
                else [outs]
            self.test_program = self.train_program.clone(for_test=True)
            loss = self.train_func_outputs[0]
            from ..optim.optimizer import Optimizer

            optimizer = optimizer_func()
            if not isinstance(optimizer, Optimizer) and \
                    not hasattr(optimizer, "minimize"):
                raise TypeError(
                    "The optimizer should be an instance of Optimizer")
            optimizer.minimize(loss)

        with scope_guard(self.scope):
            exe = Executor(self.place)
            exe.run(self.startup_program)
            if param_path:
                from ..framework.io import load_params

                load_params(exe, param_path,
                            main_program=self.train_program)
            if self.checkpoint_cfg and \
                    self.checkpoint_cfg.load_serial is not None:
                self._load_checkpoint()

    def stop(self):
        """Stop training after the current step (ref: trainer.py:373)."""
        self.__stop = True

    def train(self, num_epochs, event_handler, reader=None,
              feed_order=None):
        """Epoch/step event loop (ref: trainer.py:379)."""
        from .data_feeder import DataFeeder

        feeder = DataFeeder(feed_list=self._feed_list(feed_order))
        exe = Executor(self.place)
        fetch = self.train_func_outputs
        start_epoch = (self.checkpoint_cfg.epoch_id
                       if self.checkpoint_cfg else 0)
        # resume mid-epoch: skip the steps already applied before the
        # checkpoint so updates aren't double-applied
        resume_step = (self.checkpoint_cfg.step_id
                       if self.checkpoint_cfg else 0)
        with scope_guard(self.scope):
            for epoch_id in range(start_epoch, num_epochs):
                event_handler(BeginEpochEvent(epoch_id))
                for step_id, data in enumerate(reader()):
                    if epoch_id == start_epoch and step_id <= resume_step \
                            and resume_step > 0:
                        continue
                    if self.__stop:
                        if self.checkpoint_cfg:
                            self._clean_checkpoint()
                        return
                    begin = BeginStepEvent(epoch_id, step_id)
                    event_handler(begin)
                    metrics = exe.run(self.train_program,
                                      feed=feeder.feed(data),
                                      fetch_list=fetch
                                      if begin.fetch_metrics else [])
                    if self.checkpoint_cfg and \
                            step_id % self.checkpoint_cfg.step_interval \
                            == 0 and \
                            epoch_id % self.checkpoint_cfg.epoch_interval \
                            == 0:
                        self._save_checkpoint(epoch_id, step_id)
                    event_handler(EndStepEvent(epoch_id, step_id, metrics))
                event_handler(EndEpochEvent(epoch_id))
            if self.checkpoint_cfg:
                self._clean_checkpoint()

    def test(self, reader, feed_order=None):
        """Mean of the fetch outputs over the reader (ref:
        trainer.py:407/_test_by_executor)."""
        from .data_feeder import DataFeeder

        feeder = DataFeeder(feed_list=self._feed_list(feed_order))
        exe = Executor(self.place)
        sums, count = None, 0
        with scope_guard(self.scope):
            for data in reader():
                outs = exe.run(self.test_program,
                               feed=feeder.feed(data),
                               fetch_list=self.train_func_outputs)
                vals = [np.asarray(o, dtype=np.float64) for o in outs]
                n = len(data)
                sums = ([v * n for v in vals] if sums is None
                        else [s + v * n for s, v in zip(sums, vals)])
                count += n
        if count == 0:
            return []
        return [s / count for s in sums]

    def save_params(self, param_path):
        from ..framework.io import save_params

        with scope_guard(self.scope):
            exe = Executor(self.place)
            save_params(exe, param_path, main_program=self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        from .io import save_inference_model

        targets = [self.train_func_outputs[i]
                   for i in target_var_indexes]
        with scope_guard(self.scope):
            exe = Executor(self.place)
            save_inference_model(param_path, feeded_var_names, targets,
                                 exe, main_program=self.test_program)

    # -- internals ----------------------------------------------------------
    def _feed_list(self, feed_order):
        blk = self.train_program.global_block
        if feed_order is None:
            return [v for v in blk.vars.values()
                    if getattr(v, "is_data", False)]
        return [blk.var(n) for n in feed_order]

    def _save_checkpoint(self, epoch_id, step_id):
        from ..framework.io import save_persistables

        cfg = self.checkpoint_cfg
        serial = _latest_serial(cfg.checkpoint_dir) + 1
        d = _serial_dir(cfg, serial)
        os.makedirs(d, exist_ok=True)
        exe = Executor(self.place)
        save_persistables(exe, d, main_program=self.train_program)
        with open(os.path.join(d, "meta.json"), "w") as f:
            json.dump({"epoch_id": epoch_id, "step_id": step_id}, f)
        serials = sorted(
            int(n.split("_")[-1])
            for n in os.listdir(cfg.checkpoint_dir)
            if n.startswith("checkpoint_"))
        for old in serials[:-cfg.max_num_checkpoints]:
            shutil.rmtree(_serial_dir(cfg, old), ignore_errors=True)

    def _load_checkpoint(self):
        from ..framework.io import load_persistables

        cfg = self.checkpoint_cfg
        d = _serial_dir(cfg, cfg.load_serial)
        exe = Executor(self.place)
        load_persistables(exe, d, main_program=self.train_program)
        meta = os.path.join(d, "meta.json")
        if os.path.exists(meta):
            with open(meta) as f:
                m = json.load(f)
            cfg.epoch_id = int(m.get("epoch_id", 0))
            cfg.step_id = int(m.get("step_id", 0))

    def _clean_checkpoint(self):
        pass  # keep the last checkpoints on disk (resume-friendly)


class Inferencer:
    """ref: inferencer.py — build the net with ``infer_func`` and load
    trained params from ``param_path`` (a save_params dir). With
    ``infer_func=None``, ``param_path`` is instead a
    ``save_inference_model`` bundle served through
    ``inference.Predictor`` (the pre-existing shim contract)."""

    def __init__(self, infer_func=None, param_path=None, place=None,
                 parallel=False):
        self.scope = Scope()
        self.place = place
        self._pred = None
        if infer_func is None:
            import warnings

            warnings.warn(
                "Inferencer without infer_func serves a "
                "save_inference_model bundle; prefer "
                "paddle_tpu.inference.Predictor directly", Warning)
            from ..inference.predictor import Predictor

            self._pred = Predictor(param_path)
            return
        self.inference_program = Program()
        startup = Program()
        from ..utils import unique_name

        with _ModeGuard(), scope_guard(self.scope), \
                program_guard(self.inference_program, startup), \
                unique_name.guard():
            self.predict_var = infer_func()
        with scope_guard(self.scope):
            exe = Executor(place)
            exe.run(startup)
            from ..framework.io import load_params

            load_params(exe, param_path,
                        main_program=self.inference_program)

    def infer(self, inputs, return_numpy=True):
        """``inputs``: dict of feed name -> ndarray (ref API)."""
        if self._pred is not None:
            return self._pred.run(inputs, return_numpy=return_numpy)
        exe = Executor(self.place)
        with scope_guard(self.scope):
            return exe.run(self.inference_program, feed=inputs,
                           fetch_list=[self.predict_var],
                           return_numpy=return_numpy)
