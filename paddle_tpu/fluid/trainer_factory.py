"""fluid.trainer_factory (ref: python/paddle/fluid/trainer_factory.py).

TrainerFactory wires opt_info (trainer + device_worker class names) into
trainer_desc containers; FetchHandlerMonitor is a LIVE polling thread
that snapshots scope variables every ``handler.period_secs`` and feeds
them to a FetchHandler — same observability contract as the reference,
over our dict-backed Scope (static_/program.py).
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ..static_.executor import FetchHandler  # noqa: F401 (re-export)
from .log_helper import get_logger
from .trainer_desc import MultiTrainer, DistMultiTrainer, PipelineTrainer
from .device_worker import DeviceWorkerFactory

__all__ = ["TrainerFactory", "FetchHandler", "FetchHandlerMonitor"]

import logging

_logger = get_logger(__name__, logging.INFO,
                     fmt="%(asctime)s-%(levelname)s: %(message)s")


class TrainerFactory:
    """ref: trainer_factory.py:33 — build (trainer_desc, device_worker)
    from an optimizer's opt_info dict."""

    def _create_trainer(self, opt_info=None):
        if opt_info is None or not opt_info.get("trainer"):
            trainer = MultiTrainer()
            device_worker = DeviceWorkerFactory()._create_device_worker(
                "Hogwild")
        else:
            classes = {c.__name__: c for c in
                       (MultiTrainer, DistMultiTrainer, PipelineTrainer)}
            trainer = classes[opt_info["trainer"]]()
            device_worker = DeviceWorkerFactory()._create_device_worker(
                opt_info["device_worker"])
            if opt_info.get("use_cvm") is not None:
                trainer._set_use_cvm(opt_info["use_cvm"])
        device_worker._gen_worker_desc(trainer)
        trainer.device_worker = device_worker
        return trainer


class FetchHandlerMonitor:
    """ref: trainer_factory.py:99 — daemon thread polling the scope."""

    def __init__(self, scope, handler):
        self.fetch_instance = handler
        self._scope = scope
        self.fetch_thread = threading.Thread(
            target=self.handler_launch_func,
            args=(scope, handler), daemon=True)
        self.running = False

    def handler_launch_func(self, scope, handler):
        var_name_to_key = {}
        for key, v in handler.var_dict.items():
            name = getattr(v, "name", None)
            if name is None:
                _logger.warning(f"the value of {key} is not a Variable")
                continue
            var_name_to_key[name] = key
        elapsed = 0.0
        tick = min(0.05, handler.period_secs)
        while self.running:
            if elapsed < handler.period_secs:
                time.sleep(tick)
                elapsed += tick
                continue
            elapsed = 0.0
            res = {}
            for name, key in var_name_to_key.items():
                val = scope.find_var(name)
                res[key] = np.asarray(val) if val is not None else None
            handler.handler(res)

    def start(self):
        self.running = True
        self.fetch_thread.start()

    def stop(self):
        self.running = False
