"""Image transforms over HWC numpy arrays (see package docstring).

Ref: python/paddle/dataset/image.py — resize_short (:33 area),
center_crop, random_crop, left_right_flip, to_chw, simple_transform.
"""
from __future__ import annotations

import numpy as np


def _bilinear_resize(img, oh, ow):
    """HWC float bilinear resize (half-pixel centers), pure numpy."""
    h, w = img.shape[:2]
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    im = img if img.ndim == 3 else img[:, :, None]
    top = im[y0][:, x0] * (1 - wx) + im[y0][:, x1] * wx
    bot = im[y1][:, x0] * (1 - wx) + im[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out if img.ndim == 3 else out[:, :, 0]


def resize_short(im, size):
    """Scale so the SHORT side equals ``size`` (ref: image.py
    resize_short)."""
    h, w = im.shape[:2]
    if h < w:
        oh, ow = size, int(round(w * size / h))
    else:
        oh, ow = int(round(h * size / w)), size
    return _bilinear_resize(np.asarray(im, np.float32), oh, ow)


def center_crop(im, size, is_color=True):
    """Crop the center size x size patch (ref: image.py center_crop)."""
    h, w = im.shape[:2]
    hs = max((h - size) // 2, 0)
    ws = max((w - size) // 2, 0)
    return im[hs:hs + size, ws:ws + size]


def random_crop(im, size, is_color=True, rng=None):
    rng = rng or np.random
    h, w = im.shape[:2]
    hs = rng.randint(0, max(h - size, 0) + 1)
    ws = rng.randint(0, max(w - size, 0) + 1)
    return im[hs:hs + size, ws:ws + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def to_chw(im, order=(2, 0, 1)):
    if im.ndim == 2:
        im = im[:, :, None]
    return np.transpose(im, order)


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None, rng=None):
    """The reference's standard pipeline: resize_short -> (random|center)
    crop -> maybe flip -> CHW -> mean subtract (ref: image.py
    simple_transform)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        if (rng or np.random).randint(2):
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        im -= np.asarray(mean, np.float32).reshape(-1, 1, 1)
    return im


# -- composable transform objects (2.0-style) -------------------------------


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, im):
        for t in self.transforms:
            im = t(im)
        return im


class Resize:
    def __init__(self, size):
        self.size = size

    def __call__(self, im):
        if isinstance(self.size, int):
            return resize_short(im, self.size)
        return _bilinear_resize(np.asarray(im, np.float32),
                                self.size[0], self.size[1])


class CenterCrop:
    def __init__(self, size):
        self.size = size

    def __call__(self, im):
        return center_crop(im, self.size)


class RandomCrop:
    def __init__(self, size, seed=None):
        self.size = size
        self.rng = np.random.RandomState(seed) if seed is not None \
            else np.random

    def __call__(self, im):
        return random_crop(im, self.size, rng=self.rng)


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, seed=None):
        self.prob = prob
        self.rng = np.random.RandomState(seed) if seed is not None \
            else np.random

    def __call__(self, im):
        return left_right_flip(im) if self.rng.rand() < self.prob else im


class Normalize:
    def __init__(self, mean, std):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, im):
        shape = (-1, 1, 1) if im.ndim == 3 and im.shape[0] in (1, 3) \
            else (-1,)
        return ((np.asarray(im, np.float32)
                 - self.mean.reshape(shape)) / self.std.reshape(shape))


class ToCHW:
    def __call__(self, im):
        return to_chw(im)
