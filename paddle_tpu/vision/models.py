"""paddle.vision.models (2.x surface): the model zoo classes live in
models/vision (LeNet/ResNet/VGG/MobileNet/SSD/YOLOv3/Faster R-CNN);
this real submodule makes both ``import paddle_tpu.vision.models`` and
``from paddle_tpu.vision.models import resnet50`` work."""
from ..models.vision import *  # noqa: F401,F403
