"""paddle_tpu.vision — host-side image preprocessing.

Ref (capability target): python/paddle/dataset/image.py (resize_short,
center_crop, random_crop, left_right_flip, to_chw, simple_transform) and
the 2.0 paddle.vision.transforms composition style.

Host-side numpy on purpose: augmentation runs in the DataLoader workers
while the TPU computes the previous step, so none of this sits on the
device critical path.
"""
from .transforms import (Compose, Resize, CenterCrop, RandomCrop,
                         RandomHorizontalFlip, Normalize, ToCHW,
                         resize_short, center_crop, random_crop,
                         left_right_flip, to_chw, simple_transform)

__all__ = [
    "Compose", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "Normalize", "ToCHW",
    "resize_short", "center_crop", "random_crop", "left_right_flip",
    "to_chw", "simple_transform",
]


def __getattr__(name):
    # paddle.vision.models parity (2.x surface), loaded lazily so a bare
    # ``import paddle_tpu`` doesn't pay for the whole model zoo
    if name == "models":
        # importlib (not ``from . import``): the fromlist getattr of the
        # latter re-enters this __getattr__ mid-import and recurses
        import importlib

        return importlib.import_module(".models", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
