"""Mesh-shape description, validation, and axis-role assignment.

The planner's physical vocabulary. A **mesh shape** is what the operator
knows — "I have a 2x4 slice" / "a 2x2x2 cube" — and says nothing about
*what each axis does*. A **role assignment** gives every axis one of the
four parallelism roles the reference's fleet hybrid_configs spelled as
degrees (dp/mp/pp/ep):

- ``data``   — batch sharding; gradients all-reduce over it,
- ``model``  — tensor parallelism; weights shard, activations all-reduce,
- ``expert`` — expert parallelism; MoE expert weights shard, tokens a2a,
- ``pipe``   — pipeline stages; activations collective-permute.

Axes sharing a role merge (a 2x2x2 cube with roles (data, data, model)
IS a 4x2 dp x tp mesh — the factorization the MLPerf pod-scaling
playbook, arXiv 1909.09756, treats as the tunable), size-1 axes vanish,
and the canonical mesh orders axes ``data, model, expert, pipe`` so two
role assignments that mean the same layout build the same jax Mesh.

``candidate_assignments`` enumerates the distinct canonical layouts one
shape can express — the planner's search space. Note the shape genuinely
constrains it: 1x8 can express dp8 or tp8 but NOT dp2 x tp4.
"""
from __future__ import annotations

import itertools

import numpy as np

__all__ = [
    "ROLES", "parse_mesh_shape", "validate_mesh_shape",
    "canonical_axes", "candidate_assignments", "build_mesh",
]

# canonical role order: every mesh built here lists its axes this way,
# so identical axes dicts build identical meshes regardless of which
# raw role assignment produced them
ROLES = ("data", "model", "expert", "pipe")


def parse_mesh_shape(shape):
    """Normalize a mesh shape to a tuple of positive ints. Accepts a
    tuple/list, a single int (a 1-D mesh), or the CLI spelling
    ``"2x4"`` / ``"2,4"``."""
    if isinstance(shape, str):
        parts = shape.replace("x", ",").replace("X", ",").split(",")
        shape = [p for p in (s.strip() for s in parts) if p]
    if isinstance(shape, (int, np.integer)):
        shape = (int(shape),)
    try:
        out = tuple(int(s) for s in shape)
    except (TypeError, ValueError):
        raise ValueError(f"unparseable mesh shape {shape!r}: want e.g. "
                         "(2, 4), 8, or '2x4'") from None
    if not out or any(s < 1 for s in out):
        raise ValueError(f"mesh shape {out} must be non-empty with every "
                         "axis >= 1")
    return out


def validate_mesh_shape(shape, n_devices=None):
    """Parse + check the shape covers exactly ``n_devices`` (default:
    the process's visible devices). Returns the parsed tuple."""
    shape = parse_mesh_shape(shape)
    if n_devices is None:
        import jax

        n_devices = len(jax.devices())
    total = int(np.prod(shape))
    if total != n_devices:
        raise ValueError(
            f"mesh shape {'x'.join(map(str, shape))} covers {total} "
            f"devices but {n_devices} are available: the shape must "
            "factor the device count exactly")
    return shape


def canonical_axes(shape, roles):
    """Merge a (shape, per-axis roles) assignment into the canonical
    ``{role: size}`` dict (sizes multiplied per role, size-1 axes
    dropped, keys in ROLES order). An all-1 mesh canonicalizes to
    ``{"data": 1}`` so there is always at least one axis."""
    shape = parse_mesh_shape(shape)
    roles = tuple(roles)
    if len(roles) != len(shape):
        raise ValueError(f"{len(roles)} roles for {len(shape)} mesh axes")
    for r in roles:
        if r not in ROLES:
            raise ValueError(f"unknown axis role {r!r}: want one of "
                             f"{ROLES}")
    sizes = {}
    for s, r in zip(shape, roles):
        sizes[r] = sizes.get(r, 1) * int(s)
    out = {r: sizes[r] for r in ROLES if sizes.get(r, 1) > 1}
    return out or {"data": 1}


def candidate_assignments(shape, roles=("data", "model")):
    """All distinct canonical layouts the shape can express with the
    given role alphabet: a list of ``(roles_tuple, axes_dict)`` pairs,
    deduplicated by canonical axes (the first — most-data-major — role
    tuple wins for each layout). ``data`` is always in the alphabet:
    a planner that cannot fall back to pure DP cannot plan."""
    shape = parse_mesh_shape(shape)
    roles = tuple(dict.fromkeys(("data",) + tuple(roles)))
    seen = {}
    for combo in itertools.product(roles, repeat=len(shape)):
        axes = canonical_axes(shape, combo)
        key = tuple(sorted(axes.items()))
        if key not in seen:
            seen[key] = (combo, axes)
    return list(seen.values())


def build_mesh(axes, devices=None):
    """Build the jax Mesh for a canonical axes dict. ``devices`` defaults
    to ``jax.devices()`` truncated to the axes' product — candidates over
    a sub-mesh (e.g. dp2 x tp2 on an 8-device host) take the first
    devices, matching the hand-built dryrun recipes."""
    import jax
    from jax.sharding import Mesh

    if not axes:
        axes = {"data": 1}
    names = [n for n in ROLES if n in axes] or list(axes)
    sizes = [int(axes[n]) for n in names]
    n = int(np.prod(sizes))
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices).reshape(-1)
    if devices.size < n:
        raise ValueError(f"mesh axes {axes} need {n} devices, have "
                         f"{devices.size}")
    return Mesh(devices[:n].reshape(sizes), tuple(names))
