"""The sharding planner: Program (or Layer) + mesh shape -> ShardingPlan.

The multichip dryrun composes dp x tp x pp by hand; the reference's
``incubate/fleet`` let users say ``fleet.distributed_optimizer`` and had
the framework pick. This module is that picker, built the way the
MLPerf-on-TPU-pods playbook (arXiv 1909.09756) describes scaling: as a
*planning* problem over which mesh axes shard what, decided by a cost
model and checked against reality.

Pipeline:

1. ``analyze_program`` — one pass over the static Program: persistable
   (parameter) shapes/bytes, feed shapes, matmul sites (``linear`` /
   ``matmul`` / ``mul`` ops with a persistable weight), per-op FLOPs and
   activation bytes, and which gradient each ``optimize_*`` op consumes.
2. For every candidate role assignment of the mesh shape
   (``fleet.mesh.candidate_assignments``): assign PartitionSpecs —
   batch feeds shard over ``data``; matmul weights shard over ``model``
   in Megatron (column -> row) pairs found by a taint walk over the
   forward ops, with the column bias following its weight — and
   **predict the collective wire bytes** the compiled step will move:
   per-gradient all-reduces over ``data`` (shrunk by ``model`` sharding)
   and per-row-site activation all-reduces over ``model``, using the
   same per-participant ring-factor convention ``obs.spmd`` measures by
   (so predicted and measured are directly comparable).
3. Score candidates: predicted comm seconds (wire bytes / ICI bandwidth,
   with pure-DP grad exchanges discounted for backward overlap) plus
   compute seconds (FLOPs / (peak x devices the layout actually uses)) —
   infeasible layouts (indivisible batch / weight dims, unsharded-feed
   "data parallelism") are discarded. Lowest cost wins.
4. ``verify_plan`` — compile the winner through the REAL Executor path
   and diff prediction against the ``CollectiveProfile`` parsed from the
   executable's HLO (``obs.spmd``); the plan carries both numbers and
   the journal's ``plan`` event reports the mismatch.

The eager path (``plan_layer``) plans from the Layer's declared
``sharding_spec``s (TP layers mark their own weights): specs whose axes
a candidate lacks fall back to replicated, grads price like the static
path, and activation traffic is estimated from a ``batch_example``.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from . import mesh as _mesh

__all__ = [
    "ShardingPlan", "PlanCandidate", "analyze_program", "plan_program",
    "plan_layer", "verify_plan", "COMM_OVERLAP_DISCOUNT",
]

# ops that preserve the (data, model)-sharded layout of the activation
# flowing between a column- and a row-parallel matmul: elementwise /
# activation / dropout. Anything else consuming a tensor-sharded
# activation voids the pairing (GSPMD would insert gathers we did not
# price).
_ELEMENTWISE_CHAIN = frozenset((
    "relu", "gelu", "tanh", "sigmoid", "silu", "swish", "leaky_relu",
    "elu", "softplus", "hardswish", "hardsigmoid", "dropout",
    "dropout_axes", "alpha_dropout", "scale", "cast", "abs", "square",
    "exp", "elementwise_add", "elementwise_mul", "elementwise_sub",
    "add", "subtract", "multiply",
))

_MATMUL_OPS = frozenset(("linear", "matmul", "mul", "matmul_v2"))

# grad all-reduces over the data axis overlap the rest of the backward
# (the dist.gradcomm bucketing exists to exploit exactly that), while
# model-axis activation all-reduces sit on the layer's critical path;
# the cost model discounts overlappable traffic accordingly
COMM_OVERLAP_DISCOUNT = 0.5

# normalizing constants for the score: a v5e-class chip. Absolute
# seconds are meaningless on the CPU test rig — only the RATIO between
# compute and comm terms matters, and these keep it realistic.
_DEFAULT_PEAK = 197e12
_DEFAULT_BW = 200e9
# HBM bandwidth prices the memory term: time to touch the per-device
# high-water bytes once. Deliberately a LIGHT term — it breaks ties
# toward layouts that fit (and rejects ones that don't, see
# hbm_budget/PTA013) without drowning the comm/compute signal.
_DEFAULT_HBM_BW = 819e9


def _numel(shape):
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _dtype_bytes(dt):
    try:
        return int(np.dtype(dt).itemsize)
    except TypeError:
        return 4


@dataclasses.dataclass
class ProgramFacts:
    """What one analysis pass learned about a Program."""

    params: dict          # name -> (shape, dtype)
    feeds: dict           # name -> (shape, dtype)
    grads: list           # (grad_name, param_name, shape, dtype) consumed
    #                       by optimize_* ops — the DP exchange set
    sites: list           # matmul sites, program order (dicts, see below)
    flops: float          # rough fwd+bwd FLOPs per step
    activation_bytes: int  # sum of forward op output bytes
    forward_len: int      # ops before the first grad op


def analyze_program(program):
    """One pass over the global block (see module docstring, step 1)."""
    blk = program.global_block
    params, feeds = {}, {}
    for name, v in blk.vars.items():
        if v.persistable and not name.startswith(("@", "_")):
            params[name] = (tuple(v._data.shape), v._data.dtype)
        elif v.is_data and not name.startswith("@"):
            feeds[name] = (tuple(v._data.shape), v._data.dtype)

    ops = list(blk.ops)
    forward_len = len(ops)
    for i, op in enumerate(ops):
        if op.type.endswith("@grad") or op.type == "fill_ones_like" or \
                op.type.startswith("optimize_"):
            forward_len = i
            break

    grads = []
    for op in ops:
        if not op.type.startswith("optimize_"):
            continue
        pname = op.input_names[0]
        for n in op.input_names[1:]:
            if n is not None and n.endswith("@GRAD") and blk.has_var(n):
                g = blk.var(n)
                grads.append((n, pname, tuple(g._data.shape),
                              g._data.dtype))
                break

    sites, flops, act_bytes = [], 0.0, 0
    for i, op in enumerate(ops[:forward_len]):
        out_shapes = [tuple(blk.var(n)._data.shape)
                      for n in op.output_names if blk.has_var(n)]
        act_bytes += sum(_numel(s) * 4 for s in out_shapes)
        if op.type in _MATMUL_OPS and len(op.input_names) >= 2:
            xn, wn = op.input_names[0], op.input_names[1]
            bn = op.input_names[2] if len(op.input_names) > 2 else None
            if wn in params and blk.has_var(xn):
                w_shape = params[wn][0]
                x_shape = tuple(blk.var(xn)._data.shape)
                if len(w_shape) == 2 and len(x_shape) >= 2:
                    K, N = w_shape
                    M = _numel(x_shape[:-1])
                    xv = blk.var(xn)
                    sites.append({
                        "op_index": i, "x": xn, "w": wn,
                        "b": bn if bn in params else None,
                        "M": M, "K": int(K), "N": int(N),
                        "out": op.output_names[0],
                        "x_requires_grad": not (
                            xv.is_data or xv.stop_gradient),
                    })
                    flops += 2.0 * M * K * N
        else:
            flops += float(sum(_numel(s) for s in out_shapes))
    flops *= 3.0  # fwd + ~2x bwd, the usual accounting
    return ProgramFacts(params=params, feeds=feeds, grads=grads,
                        sites=sites, flops=flops,
                        activation_bytes=act_bytes,
                        forward_len=forward_len)


def _pair_tp_sites(facts, ops, t):
    """Find committable Megatron (column, row) matmul pairs for a model
    axis of size ``t``: column site -> elementwise chain -> row site,
    with NO other forward consumer of the sharded activation. Returns
    (pairs, specs) where specs maps weight/bias names to spec tuples."""
    pairs, specs = [], {}
    used = set()
    sites_by_index = {s["op_index"]: s for s in facts.sites}
    for site in facts.sites:
        if site["op_index"] in used or site["N"] % t:
            continue
        taint = {site["out"]}
        row = None
        ok = True
        for j in range(site["op_index"] + 1, facts.forward_len):
            op = ops[j]
            reads = [n for n in op.input_names if n in taint]
            if not reads:
                continue
            if row is not None:
                # a consumer of the sharded activation AFTER the row
                # matmul (residual/skip branch): GSPMD would gather it
                # — unpriced traffic, so the pair cannot commit
                ok = False
                break
            cand = sites_by_index.get(j)
            if cand is not None and cand["x"] in taint and \
                    cand["op_index"] not in used and \
                    cand["K"] % t == 0:
                row = cand
                continue  # keep scanning: later consumers void the pair
            if op.type in _ELEMENTWISE_CHAIN:
                taint.update(op.output_names)
                continue
            ok = False  # sharded activation leaks to an unpriced op
            break
        if ok and row is not None:
            used.add(site["op_index"])
            used.add(row["op_index"])
            pairs.append((site, row))
            specs[site["w"]] = (None, "model")
            if site["b"] is not None:
                specs[site["b"]] = ("model",)
            specs[row["w"]] = ("model", None)
            # the row bias adds AFTER the partial-sum all-reduce:
            # replicated, like its output
    return pairs, specs


def _shard_factor(spec, axes):
    n = 1
    for p in spec or ():
        for name in (p if isinstance(p, tuple) else (p,)):
            if name is not None:
                n *= axes.get(name, 1)
    return n


def _spec_fits(spec, shape, axes):
    """A spec is usable on a shape iff every named axis lands on a dim
    that exists and divides."""
    spec = tuple(spec or ())
    if len(spec) > len(shape):
        return False
    for i, p in enumerate(spec):
        for name in (p if isinstance(p, tuple) else (p,)):
            if name is None:
                continue
            if name not in axes or shape[i] % axes[name]:
                return False
    return True


@dataclasses.dataclass
class PlanCandidate:
    """One scored layout (see ShardingPlan for the chosen winner)."""

    roles: tuple
    axes: dict
    feasible: bool
    note: str = ""
    param_specs: dict = dataclasses.field(default_factory=dict)
    feed_specs: dict = dataclasses.field(default_factory=dict)
    predicted: dict = dataclasses.field(default_factory=dict)
    score: float = float("inf")
    compute_s: float = 0.0
    comm_s: float = 0.0
    mem_s: float = 0.0
    param_bytes_per_device: int = 0
    activation_bytes_per_device: int = 0
    peak_bytes_per_device: int = 0
    diagnostic: object = None  # analysis Diagnostic (PTA013) when
    #                            rejected over budget

    def summary(self):
        return {
            "axes": dict(self.axes), "roles": list(self.roles),
            "feasible": self.feasible, "note": self.note,
            "score": self.score,
            "predicted_wire_bytes":
                (self.predicted or {}).get("wire_bytes"),
            "by_axis": (self.predicted or {}).get("by_axis"),
            "param_bytes_per_device": self.param_bytes_per_device,
            "activation_bytes_per_device":
                self.activation_bytes_per_device,
            "peak_bytes_per_device": self.peak_bytes_per_device,
        }


@dataclasses.dataclass
class ShardingPlan:
    """The planner's output: a mesh layout plus per-variable
    PartitionSpecs, with its predicted (and, after ``verify_plan``,
    measured) collective traffic. The Executor consumes it via the
    ``CacheKey.plan`` axis; ``obs.journal`` records it as a ``plan``
    event per compile."""

    mesh_shape: tuple
    roles: tuple
    axes: dict                 # canonical {role: size}
    param_specs: dict          # name -> spec tuple (PartitionSpec args)
    feed_specs: dict           # name -> spec tuple
    predicted: dict            # {"wire_bytes", "by_axis", "bytes"}
    candidates: list           # every candidate's summary() for reports
    measured: dict | None = None
    source: str = "program"    # "program" | "layer"
    device_ids: tuple | None = None  # pinned placement (plan_program
    #                                  devices=), else first-N default
    peak_bytes_per_device: int | None = None  # winner's predicted
    #                                  per-device peak HBM (analysis.memory)

    @property
    def is_pure_dp(self):
        return set(self.axes) <= {"data"}

    @property
    def data_size(self):
        return int(self.axes.get("data", 1))

    @property
    def predicted_wire_bytes(self):
        return (self.predicted or {}).get("wire_bytes")

    @property
    def measured_wire_bytes(self):
        return (self.measured or {}).get("wire_bytes")

    @property
    def mismatch(self):
        """Relative |predicted - measured| / measured, None until
        verified (or when the step measures zero traffic)."""
        p, m = self.predicted_wire_bytes, self.measured_wire_bytes
        if p is None or not m:
            return None
        return abs(p - m) / m

    def spec_for(self, name, shape=None):
        """PartitionSpec args for one persistable. Optimizer slots
        (``<param>@OPT@<k>``) and gradcomm state follow their param
        when shaped like it; anything unknown (or that no longer fits
        its shape) replicates."""
        spec = self.param_specs.get(name)
        if spec is None and "@OPT@" in name:
            spec = self.param_specs.get(name.split("@OPT@")[0])
        spec = tuple(spec or ())
        if shape is not None and not _spec_fits(spec, shape, self.axes):
            return ()
        return spec

    def feed_spec_for(self, name, shape=None):
        spec = self.feed_specs.get(name)
        if spec is None:
            return ()
        spec = tuple(spec)
        if shape is not None and not _spec_fits(spec, shape, self.axes):
            return ()
        return spec

    def build_mesh(self, devices=None):
        if devices is None and self.device_ids is not None:
            import jax

            by_id = {d.id: d for d in jax.devices()}
            devices = [by_id[i] for i in self.device_ids]
        return _mesh.build_mesh(self.axes, devices=devices)

    def cache_axis(self):
        """Hashable identity for the Executor CacheKey ``plan`` axis:
        everything that changes the compiled executable."""
        return (self.device_ids, tuple(self.mesh_shape),
                tuple(self.roles),
                tuple(sorted(self.axes.items())),
                tuple(sorted((k, tuple(v))
                             for k, v in self.param_specs.items())),
                tuple(sorted((k, tuple(v))
                             for k, v in self.feed_specs.items())))

    def event_fields(self, **extra):
        """The journal ``plan`` event payload (one shape, used by the
        Executor compile hook and the eager path alike)."""
        out = {
            "mesh_shape": list(self.mesh_shape),
            "roles": list(self.roles),
            "axes": dict(self.axes),
            "source": self.source,
            "predicted_wire_bytes": self.predicted_wire_bytes,
            "measured_wire_bytes": self.measured_wire_bytes,
            "mismatch": self.mismatch,
            "peak_bytes_per_device": self.peak_bytes_per_device,
        }
        out.update(extra)
        return out


def _wire(kind, n, payload):
    """Per-participant wire bytes, obs.spmd's ring-factor convention."""
    from ..obs.spmd import wire_factor

    return payload * wire_factor(kind, n)


def _over_budget(cand, peak_pd, hbm_budget):
    """Reject one candidate whose per-device peak exceeds the HBM
    budget: infeasible, PTA013-coded (the planner's analog of an OOM
    at compile time, caught before any XLA work)."""
    from ..analysis.diagnostics import Diagnostic, ERROR

    cand.feasible = False
    cand.note = (f"[PTA013] predicted peak {peak_pd} B/device exceeds "
                 f"the HBM budget {int(hbm_budget)} B")
    cand.diagnostic = Diagnostic(
        "PTA013", ERROR,
        f"layout {cand.axes} needs {peak_pd} B/device but the budget "
        f"is {int(hbm_budget)} B: over-budget layout rejected as "
        "infeasible", pass_name="planner")
    return cand


def _score_candidate(cand, facts, ops, peak, bw, mem_profile=None,
                     hbm_budget=None, hbm_bw=_DEFAULT_HBM_BW):
    """Fill specs + predicted traffic + score for one candidate over a
    static Program's facts. Mutates and returns ``cand``.

    ``mem_profile`` is ``analysis.memory.candidate_peak``'s
    ``(act_peak_bytes, const_bytes)`` — one liveness walk, shared by
    every candidate; per-candidate division happens here (params by
    their spec's shard factor, batch feeds and the activation peak by
    the data axis)."""
    axes = cand.axes
    d = int(axes.get("data", 1))
    t = int(axes.get("model", 1))
    for role in axes:
        if role not in ("data", "model"):
            cand.feasible = False
            cand.note = (f"role {role!r} needs runtime structure "
                         "(MoE/pipeline) the static planner does not "
                         "shard")
            return cand

    # feeds: shard the leading (batch) dim over data
    feed_specs = {}
    sharded_feed = False
    for name, (shape, _dt) in facts.feeds.items():
        if d > 1 and len(shape) >= 1 and shape[0] > 0 and \
                shape[0] % d == 0:
            feed_specs[name] = ("data",)
            sharded_feed = True
        else:
            feed_specs[name] = ()
    if d > 1 and facts.feeds and not sharded_feed:
        cand.feasible = False
        cand.note = (f"no feed's leading dim divides the {d}-way data "
                     "axis (the step would run replicated)")
        return cand

    # model axis: committable Megatron pairs
    param_specs = {}
    pairs = []
    if t > 1:
        pairs, param_specs = _pair_tp_sites(facts, ops, t)
        if not pairs:
            cand.feasible = False
            cand.note = (f"model axis of {t} finds no committable "
                         "column->row matmul pair (indivisible dims or "
                         "leaky activation consumers)")
            return cand

    # -- predicted wire bytes (per-participant, obs.spmd convention) --
    by_axis = {}
    wire_overlappable = 0.0
    wire_critical = 0.0
    if d > 1:
        g_bytes = 0.0
        for _gname, pname, shape, dt in facts.grads:
            f = _shard_factor(param_specs.get(pname), axes)
            g_bytes += _numel(shape) * _dtype_bytes(dt) / f
        w = _wire("all-reduce", d, g_bytes)
        by_axis["data"] = by_axis.get("data", 0.0) + w
        wire_overlappable += w
    if t > 1:
        a_bytes = 0.0
        for col, row in pairs:
            # forward: the row matmul's partial-sum all-reduce
            a_bytes += (row["M"] // d if d > 1 else row["M"]) * \
                row["N"] * 4
            # backward: the column input's gradient all-reduce (absent
            # when the input is a feed — XLA DCEs the unused dx)
            if col["x_requires_grad"]:
                a_bytes += (col["M"] // d if d > 1 else col["M"]) * \
                    col["K"] * 4
        w = _wire("all-reduce", t, a_bytes)
        by_axis["model"] = by_axis.get("model", 0.0) + w
        wire_critical += w

    wire = wire_overlappable + wire_critical
    cand.param_specs = param_specs
    cand.feed_specs = feed_specs
    cand.predicted = {
        "wire_bytes": int(round(wire)),
        "by_axis": {k: int(round(v)) for k, v in by_axis.items()},
        "bytes": {"all-reduce": int(round(wire))},
        "tp_pairs": len(pairs),
    }

    # -- score: comm (overlap-discounted) + compute over exploited axes
    effective = d * (t if pairs else 1)
    cand.compute_s = facts.flops / (peak * effective)
    cand.comm_s = (wire_critical +
                   COMM_OVERLAP_DISCOUNT * wire_overlappable) / bw
    cand.score = cand.compute_s + cand.comm_s
    cand.feasible = True
    cand.note = f"{len(pairs)} tp pair(s)" if pairs else "pure dp"

    # -- memory: per-device peak (analysis.memory liveness walk) is a
    # PRICED cost term now — time to touch the high-water bytes once
    # over HBM bandwidth — and a feasibility constraint under
    # hbm_budget (PTA013). Params divide by their spec's shard factor,
    # batch feeds and the activation peak by the data axis; the model
    # axis's activation sharding is left unpriced (a conservative
    # over-estimate).
    pb = 0
    for name, (shape, dt) in facts.params.items():
        pb += _numel(shape) * _dtype_bytes(dt) // \
            _shard_factor(param_specs.get(name), axes)
    cand.param_bytes_per_device = pb
    cand.activation_bytes_per_device = int(
        facts.activation_bytes // (d if d > 1 else 1))
    if mem_profile is not None:
        act_peak, const_b = mem_profile
        feed_pd = 0
        for name, (shape, dt) in facts.feeds.items():
            f = d if feed_specs.get(name) == ("data",) else 1
            feed_pd += _numel(shape) * _dtype_bytes(dt) // f
        peak_pd = int(pb + feed_pd + const_b + act_peak // (d or 1))
        cand.peak_bytes_per_device = peak_pd
        cand.mem_s = peak_pd / hbm_bw
        cand.score += cand.mem_s
        if hbm_budget and peak_pd > hbm_budget:
            return _over_budget(cand, peak_pd, hbm_budget)
    return cand


def plan_program(program, mesh_shape, roles=None, devices=None,
                 peak=None, bw=None, hbm_budget=None):
    """Plan a static Program onto ``mesh_shape``. ``roles`` pins the
    per-axis role assignment (the operator knows the topology); left
    None, every canonical assignment over {data, model} is scored and
    the cheapest feasible one wins. Raises when nothing is feasible.

    ``hbm_budget`` (bytes per device; env ``PADDLE_TPU_HBM_BUDGET``
    when None) rejects candidates whose predicted per-device peak HBM
    (``analysis.memory`` liveness walk) exceeds it — each rejection
    carries a PTA013 diagnostic, and a mesh where EVERY layout is over
    budget raises with the PTA013 notes instead of compiling a layout
    that OOMs."""
    n_devices = device_ids = None
    if devices is not None:
        devs = np.asarray(devices).reshape(-1)
        n_devices = int(devs.size)
        # pin the placement: build_mesh (and the Executor compiling
        # under this plan) lays out over THESE devices, not the
        # first-N default
        device_ids = tuple(int(d.id) for d in devs)
    shape = _mesh.validate_mesh_shape(mesh_shape, n_devices=n_devices)
    facts = analyze_program(program)
    ops = list(program.global_block.ops)
    peak = peak or _DEFAULT_PEAK
    bw = bw or _ici_bw_or_default()
    if hbm_budget is None:
        hbm_budget = _hbm_budget_env()
    from ..analysis.memory import candidate_peak

    mem_profile = candidate_peak(program, ops=ops)

    if roles is not None:
        assignments = [(tuple(roles),
                        _mesh.canonical_axes(shape, roles))]
    else:
        assignments = _mesh.candidate_assignments(shape)
    cands = [_score_candidate(
        PlanCandidate(roles=r, axes=a, feasible=False), facts, ops,
        peak, bw, mem_profile=mem_profile, hbm_budget=hbm_budget)
        for r, a in assignments]
    feasible = [c for c in cands if c.feasible]
    if not feasible:
        detail = "; ".join(f"{c.axes}: {c.note}" for c in cands)
        raise ValueError(
            f"no feasible layout for mesh {shape} on this program "
            f"({detail})")
    best = min(feasible, key=lambda c: c.score)
    return ShardingPlan(
        mesh_shape=shape, roles=best.roles, axes=dict(best.axes),
        param_specs=dict(best.param_specs),
        feed_specs=dict(best.feed_specs),
        predicted=dict(best.predicted),
        candidates=[c.summary() for c in cands], source="program",
        device_ids=device_ids,
        peak_bytes_per_device=best.peak_bytes_per_device or None)


def _hbm_budget_env():
    import os

    env = os.environ.get("PADDLE_TPU_HBM_BUDGET", "")
    if not env:
        return None
    try:
        return float(env)
    except ValueError:
        # a typo'd budget must not SILENTLY disable the OOM guard the
        # operator believes is active
        import warnings

        warnings.warn(
            f"PADDLE_TPU_HBM_BUDGET={env!r} is not a number (bytes); "
            "planning WITHOUT a per-device HBM budget — over-budget "
            "layouts will not be rejected", RuntimeWarning)
        return None


def _ici_bw_or_default():
    from ..obs.spmd import ici_bandwidth

    return ici_bandwidth() or _DEFAULT_BW


# -- eager path ---------------------------------------------------------------


def plan_layer(model, mesh_shape, roles=None, batch_example=None,
               peak=None, bw=None, hbm_budget=None):
    """Plan an eager Layer onto ``mesh_shape`` from its parameters'
    declared ``sharding_spec``s (TP/MoE layers mark their own weights —
    the planner decides which declared axes the mesh affords). Gradient
    traffic prices like the static path; activation traffic for the
    model axis is estimated from ``batch_example`` (arrays or shapes)
    as one partial-sum all-reduce per row-sharded weight.
    ``hbm_budget`` rejects candidates over the per-device byte budget
    (PTA013) — the eager proxy is param bytes per device plus the
    batch example (no recorded op list to walk)."""
    shape = _mesh.parse_mesh_shape(mesh_shape)
    params = []
    for name, p in model.named_parameters():
        # the DECLARED spec: auto_parallel_step stashes the original
        # under _declared_sharding_spec before installing the plan's
        # placements, so replanning reads the layer's declaration, not
        # a previous plan's output
        spec = getattr(p, "_declared_sharding_spec",
                       getattr(p, "sharding_spec", None))
        params.append((name, p, tuple(p._data.shape), spec))
    declared_axes = set()
    for _n, _p, _shape, spec in params:
        for part in tuple(spec or ()):
            for ax in (part if isinstance(part, tuple) else (part,)):
                if ax is not None:
                    declared_axes.add(ax)
    alphabet = tuple(r for r in ("data", "model", "expert", "pipe")
                     if r == "data" or r in declared_axes)
    m_tokens = batch_dim = None
    if batch_example is not None:
        first = batch_example[0] if isinstance(
            batch_example, (tuple, list)) else batch_example
        bshape = tuple(getattr(first, "shape", first))
        m_tokens = _numel(bshape[:2]) if len(bshape) >= 2 else \
            _numel(bshape)
        batch_dim = int(bshape[0]) if bshape else None
    peak = peak or _DEFAULT_PEAK
    bw = bw or _ici_bw_or_default()
    if hbm_budget is None:
        hbm_budget = _hbm_budget_env()

    if roles is not None:
        assignments = [(tuple(roles),
                        _mesh.canonical_axes(shape, roles))]
    else:
        assignments = _mesh.candidate_assignments(shape, roles=alphabet)

    cands = []
    for r, axes in assignments:
        cand = PlanCandidate(roles=r, axes=axes, feasible=True)
        d = int(axes.get("data", 1))
        if d > 1 and batch_dim is not None and batch_dim % d:
            # the step would fail at device_put — infeasible at plan
            # time, like the static path's feed-divisibility guard
            cand.feasible = False
            cand.note = (f"batch dim {batch_dim} does not divide the "
                         f"{d}-way data axis")
            cands.append(cand)
            continue
        specs = {}
        used_axes = set()
        for name, _p, pshape, spec in params:
            spec = tuple(spec or ())
            if spec and _spec_fits(spec, pshape, axes):
                specs[name] = spec
                for part in spec:
                    for ax in (part if isinstance(part, tuple)
                               else (part,)):
                        if ax is not None:
                            used_axes.add(ax)
            else:
                specs[name] = ()
        idle = [a for a in axes if a != "data" and a not in used_axes]
        if idle:
            cand.feasible = False
            cand.note = f"axes {idle} shard no parameter"
            cands.append(cand)
            continue
        g_bytes = sum(_numel(pshape) * 4 / _shard_factor(specs[n], axes)
                      for n, _p, pshape, _s in params)
        wire_ov = _wire("all-reduce", d, g_bytes) if d > 1 else 0.0
        wire_cr = 0.0
        by_axis = {}
        if wire_ov:
            by_axis["data"] = int(round(wire_ov))
        for ax in axes:
            if ax in ("data",):
                continue
            n_ax = axes[ax]
            if m_tokens:
                # one partial-sum AR per row-sharded (dim-0) 2D weight
                a_bytes = 0.0
                for n, _p, pshape, _s in params:
                    sp = specs[n]
                    if len(pshape) >= 2 and sp and sp[0] is not None \
                            and ax in (sp[0] if isinstance(sp[0], tuple)
                                       else (sp[0],)):
                        a_bytes += (m_tokens // d) * pshape[-1] * 4
                w = _wire("all-reduce", n_ax, a_bytes)
                by_axis[ax] = int(round(w))
                wire_cr += w
        cand.param_specs = specs
        cand.predicted = {
            "wire_bytes": int(round(wire_ov + wire_cr)),
            "by_axis": by_axis, "bytes": {},
        }
        eff = d
        for ax, n_ax in axes.items():
            if ax != "data" and ax in used_axes:
                eff *= int(n_ax)
        # 6ND transformer accounting over the TOTAL (unsharded) param
        # count — the per-device speedup lives in eff alone, never in
        # the numerator (g_bytes is already sharded; reusing it here
        # would double-count the model-axis split)
        total_numel = sum(_numel(pshape) for _n, _p, pshape, _s in params)
        flops = 6.0 * total_numel * (m_tokens or 1)
        cand.compute_s = flops / (peak * max(eff, 1))
        cand.comm_s = (wire_cr + COMM_OVERLAP_DISCOUNT * wire_ov) / bw
        cand.score = cand.compute_s + cand.comm_s
        cand.param_bytes_per_device = int(g_bytes)
        # eager per-device peak proxy: sharded params + the batch
        # shard (no recorded op list to liveness-walk)
        batch_b = (m_tokens or 0) * 4
        peak_pd = int(g_bytes + batch_b // d)
        cand.peak_bytes_per_device = peak_pd
        cand.mem_s = peak_pd / _DEFAULT_HBM_BW
        cand.score += cand.mem_s
        cand.note = "declared specs" if used_axes else "pure dp"
        if hbm_budget and peak_pd > hbm_budget:
            _over_budget(cand, peak_pd, hbm_budget)
        cands.append(cand)

    feasible = [c for c in cands if c.feasible]
    if not feasible:
        detail = "; ".join(f"{c.axes}: {c.note}" for c in cands)
        raise ValueError(f"no feasible layout for mesh {shape} on this "
                         f"model ({detail})")
    best = min(feasible, key=lambda c: c.score)
    return ShardingPlan(
        mesh_shape=shape, roles=best.roles, axes=dict(best.axes),
        param_specs=dict(best.param_specs), feed_specs={},
        predicted=dict(best.predicted),
        candidates=[c.summary() for c in cands], source="layer",
        peak_bytes_per_device=best.peak_bytes_per_device or None)


# -- verification -------------------------------------------------------------


def verify_plan(plan, program, executor=None, fetch_list=None):
    """Compile ``program`` under ``plan`` through the real Executor path
    and fill ``plan.measured`` from the executable's CollectiveProfile
    (``obs.spmd``). BLOCKING — pays one XLA compile; call it from
    planning/reporting code, never the step path. Requires the startup
    program to have run (persistables materialized in the scope).
    Returns the measured profile (or None when analysis fails)."""
    import jax

    from ..obs.mfu import entry_analysis
    from ..static_.executor import Executor

    exe = executor or Executor()
    feeds = {name: jax.ShapeDtypeStruct(shape, np.dtype(dt))
             for name, (shape, dt) in
             analyze_program(program).feeds.items()}
    if program._lr_getter is not None:
        # Executor.run injects the scheduler lr each step; the probe
        # compile must present the same feed surface
        feeds["@lr"] = jax.ShapeDtypeStruct((), np.float32)
    compiled = exe._compile(program, feeds, fetch_list or [],
                            data_parallel=True, plan=plan)
    prof = (entry_analysis(compiled) or {}).get("collectives")
    if prof:
        plan.measured = {
            "wire_bytes": prof.get("wire_bytes"),
            "by_axis": prof.get("by_axis"),
            "counts": prof.get("counts"),
            "bytes": prof.get("bytes"),
        }
        from ..obs import journal as _journal

        if _journal.ACTIVE is not None:
            # the probe compile's plan event above predated the
            # measurement; journal the verified record (predicted AND
            # measured) so reports don't read the plan as unverified
            _journal.ACTIVE.record_plan(plan, uid=program._uid,
                                        version=program._version,
                                        verified=True)
    return prof
