"""paddle_tpu.fleet — mesh-aware auto-parallel, Program to pod scale.

The reference's ``incubate/fleet`` gave users one call
(``fleet.distributed_optimizer``) and picked the distributed layout for
them; the multichip dryrun here composed dp x tp x pp by hand instead.
This package closes that gap:

- ``fleet.mesh``    — mesh-shape description/validation and axis-role
  assignment (data / model / expert / pipe) for shapes like 1x8, 2x4,
  2x2x2, with canonical merging so equivalent assignments coincide.
- ``fleet.planner`` — walks a static Program (or an eager Layer's
  declared specs), enumerates candidate layouts, scores them with a
  cost model over per-op FLOPs, parameter/activation bytes, and
  predicted collective wire bytes, and verifies the winner against the
  ``obs.spmd`` CollectiveProfile parsed from the compiled HLO.
- ``fleet.api``     — ``auto_parallel(program, mesh_shape)`` (static,
  Executor-compiled under a plan-keyed cache entry, gradcomm-composable
  when pure-DP) and ``auto_parallel_step(model, opt, loss_fn,
  mesh_shape)`` (eager, DistributedTrainStep over the plan's mesh).

Old-API compatibility: the pre-plan fleet surface — ``fleet.init``,
``DistributedStrategy``, ``distributed_optimizer``, worker queries — is
re-exported from ``dist.fleet`` unchanged, so reference-era fleet code
keeps running (see MIGRATING.md).

Tooling: ``tools/fleet_plan.py`` prints the candidate table (predicted
vs HLO-measured bytes per candidate, per-device memory); the journal
records a ``plan`` event per auto-parallel compile and
``tools/run_report.py`` renders/diffs it.
"""
from __future__ import annotations

# old fleet surface, preserved verbatim (ref: incubate/fleet)
from ..dist.fleet import (  # noqa: F401
    DistributedStrategy, fleet, init, distributed_optimizer,
    worker_num, worker_index, is_first_worker,
)

# the new auto-parallel surface
from .mesh import (  # noqa: F401
    ROLES, parse_mesh_shape, validate_mesh_shape, canonical_axes,
    candidate_assignments, build_mesh,
)
from .planner import (  # noqa: F401
    ShardingPlan, PlanCandidate, analyze_program, plan_program,
    plan_layer, verify_plan,
)
from .api import (  # noqa: F401
    AutoParallelProgram, auto_parallel, auto_parallel_step,
)

__all__ = [
    # old API (dist.fleet shims)
    "DistributedStrategy", "fleet", "init", "distributed_optimizer",
    "worker_num", "worker_index", "is_first_worker",
    # mesh
    "ROLES", "parse_mesh_shape", "validate_mesh_shape",
    "canonical_axes", "candidate_assignments", "build_mesh",
    # planner
    "ShardingPlan", "PlanCandidate", "analyze_program", "plan_program",
    "plan_layer", "verify_plan",
    # api
    "AutoParallelProgram", "auto_parallel", "auto_parallel_step",
]


def __getattr__(name):
    """PEP 562: the rest of the pre-plan singleton surface (strategy,
    init_worker, build_train_step, barrier_worker, ...) forwards to
    ``dist.fleet`` so this package is a strict superset of the module
    it replaces as the ``paddle_tpu.fleet`` alias."""
    from ..dist import fleet as _old

    try:
        return getattr(_old, name)
    except AttributeError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
