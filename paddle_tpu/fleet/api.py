"""fleet user API: one call from Program (or Layer) + mesh shape to a
running auto-parallel step.

Static path (the reference's ``CompiledProgram.with_data_parallel``
idiom, plan-aware)::

    compiled = fleet.auto_parallel(main_prog, mesh_shape=(2, 4))
    exe.run(compiled, feed=..., fetch_list=[loss])

``auto_parallel`` plans (``fleet.planner``), optionally verifies the
winner's predicted wire bytes against the compiled HLO's
CollectiveProfile, and returns a CompiledProgram the Executor compiles
under a plan-keyed cache entry (``CacheKey.plan``) with the plan's
shardings. A pure-DP plan composes with the ``dist.gradcomm``
comm-efficient exchange via ``comm_options`` exactly like
``with_data_parallel(comm_options=...)``.

Eager path (the reference's ``fleet.distributed_optimizer`` idiom)::

    step = fleet.auto_parallel_step(model, opt, loss_fn,
                                    mesh_shape=(2, 2))
    loss = step(x, y)

plans from the Layer's declared ``sharding_spec``s and builds a
``DistributedTrainStep`` over the plan's mesh.

The pre-plan fleet surface (``fleet.init`` / ``DistributedStrategy`` /
worker queries) is re-exported unchanged from ``dist.fleet`` — old
fleet code keeps working, MIGRATING.md documents the mapping.
"""
from __future__ import annotations

from ..obs import journal as _journal
from ..static_.compiler import CompiledProgram
from .planner import plan_layer, plan_program, verify_plan

__all__ = ["AutoParallelProgram", "auto_parallel", "auto_parallel_step"]


class AutoParallelProgram(CompiledProgram):
    """A CompiledProgram carrying the planner's ShardingPlan as
    ``._plan``: the Executor compiles it under a plan-keyed cache entry
    (``CacheKey.plan``) with the plan's shardings instead of the
    one-axis ``with_data_parallel`` default."""

    def __init__(self, program, plan, comm_options=None):
        super().__init__(program)
        self._data_parallel = True
        self._plan = plan
        if comm_options is not None:
            self._build_strategy.comm_options = comm_options


def auto_parallel(program, mesh_shape, roles=None, comm_options=None,
                  verify=True, fetch_list=None, executor=None,
                  peak=None, bw=None, hbm_budget=None):
    """Plan ``program`` onto ``mesh_shape`` and return a data-parallel
    CompiledProgram the Executor runs under the plan's shardings.

    ``roles`` pins per-axis roles (e.g. ``("data", "model")``); left
    None the planner scores every canonical assignment. ``verify=True``
    (default) compiles once through the real Executor path and fills
    ``plan.measured`` from the executable's CollectiveProfile — call it
    AFTER the startup program has materialized the parameters. The
    probe compile is paid once per plan; pass ``executor=`` (your run
    executor) and ``fetch_list=`` (your run's fetches) to turn it into
    a warm cache entry the first real ``exe.run`` hits, or
    ``verify=False`` to skip it entirely. With an AOT executable cache
    active (``runtime.aot``: ``set_compilation_cache`` / env
    ``PADDLE_TPU_AOT_CACHE``) the probe also PUBLISHES the
    plan-carrying executable to disk, so every later process — each
    replica of a fleet — hydrates it instead of recompiling.
    ``comm_options`` (dist.gradcomm) requires the plan to be pure DP.
    ``hbm_budget`` (bytes per device; env ``PADDLE_TPU_HBM_BUDGET``)
    rejects layouts whose predicted per-device peak HBM exceeds it
    (PTA013) before any compile. The returned object exposes the plan
    as ``._plan``.
    """
    plan = plan_program(program, mesh_shape, roles=roles, peak=peak,
                        bw=bw, hbm_budget=hbm_budget)
    if comm_options is not None and not plan.is_pure_dp:
        raise ValueError(
            "comm_options (dist.gradcomm) composes only with a pure "
            f"data-parallel plan; the planner chose {plan.axes}. Pin "
            "roles=('data',)*len(mesh_shape) to force pure DP")
    if verify:
        verify_plan(plan, program, executor=executor,
                    fetch_list=fetch_list)
    return AutoParallelProgram(program, plan,
                               comm_options=comm_options)


def auto_parallel_step(model, optimizer, loss_fn, mesh_shape,
                       roles=None, batch_example=None, devices=None,
                       hbm_budget=None, **step_kw):
    """Plan an eager Layer onto ``mesh_shape`` and return a
    ``DistributedTrainStep`` over the plan's mesh with the plan's
    parameter placements installed (declared TP/MoE ``sharding_spec``s
    the mesh affords are kept; the rest replicate). Extra keyword args
    pass through to DistributedTrainStep. The step exposes the plan as
    ``.plan``; its measured collective mix comes from
    ``step.collective_profile()`` after the first call."""
    from jax.sharding import PartitionSpec as P

    from ..dist.parallel import DistributedTrainStep

    plan = plan_layer(model, mesh_shape, roles=roles,
                      batch_example=batch_example,
                      hbm_budget=hbm_budget)
    mesh = plan.build_mesh(devices=devices)
    for name, p in model.named_parameters():
        # stash the model's DECLARED spec once (plan_layer plans from
        # it) so replanning the same model onto another mesh — or a
        # plan that replicates this param — never erases the layer's
        # TP/MoE declaration
        if not hasattr(p, "_declared_sharding_spec"):
            p._declared_sharding_spec = getattr(p, "sharding_spec", None)
        p.sharding_spec = P(*plan.param_specs.get(name, ()))
    # a pure-TP/EP plan has no data axis: the batch replicates (every
    # device computes the full batch; the model axes shard the math)
    step_kw.setdefault("batch_axis",
                       "data" if "data" in plan.axes else None)
    step = DistributedTrainStep(model, optimizer, loss_fn, mesh=mesh,
                                **step_kw)
    step.plan = plan
    if _journal.ACTIVE is not None:
        _journal.ACTIVE.record_plan(plan)
    return step
