"""paddle_tpu: a TPU-native deep-learning framework.

Re-designed from scratch for TPU (jax/XLA/pallas/pjit) with the API surface
and capabilities of the PaddlePaddle Fluid reference (gc1023/Paddle):
eager (dygraph) + static (Program/Executor) modes, nn layers, optimizers,
data pipeline, Mesh-based distributed training (dp/tp/pp/sp/ep), AMP,
checkpointing, inference, and a model zoo.
"""
from __future__ import annotations

__version__ = "0.1.0"
from . import version  # noqa: F401,E402

import os as _os
from .check_import_scipy import check_import_scipy  # noqa: E402

check_import_scipy(_os.name)

# jax < 0.4.38 ships shard_map under jax.experimental only; every shard_map
# call site here (dist.moe / pipeline / ring_attention / ulysses /
# collective) and downstream user code spells it jax.shard_map, the name
# newer jax promoted to the top level. Alias it once at import so both
# spellings work on the pinned 0.4.37, translating the renamed keywords:
# new axis_names={manual axes} is old auto={the other mesh axes}, new
# check_vma= is old check_rep=. jax.lax.axis_size (also newer) is the
# psum(1, axis) identity, which jax constant-folds to the axis size.
import jax as _jax  # noqa: E402

if not hasattr(_jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map_compat(f, mesh=None, in_specs=None, out_specs=None,
                          axis_names=None, check_vma=None, **kw):
        if axis_names is not None and "auto" not in kw:
            kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        if check_vma is not None and "check_rep" not in kw:
            kw["check_rep"] = check_vma
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)

    # capability marker: dist.pipeline.partial_manual_supported() keys
    # off this to refuse (fast, with a message) the partial-auto paths
    # this jax/XLA line cannot compile
    _shard_map_compat._paddle_tpu_compat = True
    _jax.shard_map = _shard_map_compat

if not hasattr(_jax.lax, "axis_size"):
    def _axis_size(axis_name):
        return _jax.lax.psum(1, axis_name)

    _jax.lax.axis_size = _axis_size

if not hasattr(_jax.lax, "pcast"):
    # newer jax tracks varying-over-axis (vma) types inside shard_map and
    # needs explicit casts; 0.4.37 has no vma typing, so the cast is an
    # identity
    def _pcast(x, axis_name=None, to=None, **_kw):
        return x

    _jax.lax.pcast = _pcast

from .core import (
    Tensor,
    Parameter,
    no_grad,
    enable_grad,
    is_grad_enabled,
)
from .core.autograd import grad
from .core.tensor import to_tensor
from .core import dtype as _dtype_mod
from .core.dtype import (
    float16, bfloat16, float32, float64, int8, int16, int32, int64, uint8,
    bool_, complex64, complex128, set_default_dtype, get_default_dtype,
)
from .core.device import (
    set_device, get_device, device_count, is_compiled_with_tpu,
    TPUPlace, CPUPlace, CUDAPlace, Place, set_compilation_cache,
)
from .core.random import seed

# ops: import attaches Tensor methods, then re-export the functional API
from . import ops
from .ops.creation import (
    zeros, ones, full, empty, zeros_like, ones_like, full_like, empty_like,
    arange, linspace, logspace, eye, tril, triu, meshgrid, diagflat, assign,
    clone, rand, randn, randint, randperm, uniform, normal, bernoulli,
    multinomial, standard_normal, fill_constant,
)
from .ops.math import (
    add, subtract, multiply, divide, floor_divide, remainder, mod, pow,
    matmul, mm, bmm, dot, outer, inner, scale, clip, add_n, cumsum, cumprod,
    lerp, einsum, kron, trace, diag, diagonal, nan_to_num, stanh, exp, expm1,
    log, log2, log10, log1p, sqrt, rsqrt, abs, neg, floor, ceil, round, trunc,
    sin, cos, tan, asin, acos, atan, sinh, cosh, asinh, acosh, atanh, erf,
    erfinv, sign, reciprocal, square, digamma, lgamma, isnan, isinf, isfinite,
    maximum, minimum, atan2, logaddexp, increment, mul,
)
from .ops.reduction import (
    sum, mean, max, min, prod, all, any, logsumexp, argmax, argmin, std, var,
    median, quantile, kthvalue, mode as mode_op, count_nonzero, nansum,
    nanmean, amax, amin,
)
from .ops.manipulation import (
    reshape, transpose, t, flatten, squeeze, unsqueeze, concat, stack, split,
    chunk, unbind, slice, strided_slice, gather, gather_nd, take_along_axis,
    index_select, index_sample, scatter, scatter_nd, scatter_nd_add,
    put_along_axis, tile, expand, broadcast_to, expand_as, repeat_interleave,
    flip, roll, pad, where, topk, sort, argsort, one_hot, cast, nonzero,
    masked_select, unique, masked_fill, bincount, moveaxis, swapaxes, rot90,
    shard_index, as_real, as_complex,
)
from .ops.compare import (
    equal, not_equal, less_than, less_equal, greater_than, greater_equal,
    logical_and, logical_or, logical_xor, logical_not, bitwise_and,
    bitwise_or, bitwise_xor, bitwise_not, isclose, allclose, equal_all,
    is_empty, is_tensor,
)
from .ops.activation import tanh  # noqa: F401  (others live in nn.functional)
from .ops.linalg import (
    norm, dist, cholesky, inverse, matrix_power, pinv, svd, qr, eig, eigh,
    eigvals, eigvalsh, matrix_rank, det, slogdet, cross, triangular_solve,
    cholesky_solve, solve, lstsq, histogram, mv, multi_dot, cov, corrcoef,
)
from .ops.control_flow import cond, while_loop, case, switch_case, scan

from . import nn
from . import optim
from . import amp
from . import metrics
from . import distribution
from . import static_
from . import framework
from . import resilience
from . import obs
from . import runtime
from . import inference
from . import serving
from . import quant
from . import slim
from . import hapi
from . import dataset
from . import vision
from . import fluid
from .hapi import Model
from .io_.dataloader import DataLoader  # noqa: F401  (paddle.DataLoader)
# NB: ``paddle_tpu.dist`` is the p-norm distance op (paddle parity);
# the distributed package binds as ``paddle_tpu.distributed`` — that
# alias, and the rest of the 2.x module surface (paddle.tensor, .io,
# .metric, .optimizer, .static, .device, .fleet, .imperative,
# .regularizer), are bound by modules_compat.install() at the bottom
# of this file so the alias table lives in ONE place.
from . import sysconfig  # noqa: E402


def summary(net, input_size, dtypes="float32"):
    """Per-layer param/FLOP table (2.x ``paddle.summary`` shape; built
    on utils.stats.summary — forward hooks over a sample run)."""
    from .utils.stats import summary as _s

    return _s(net, input_size, dtypes=dtypes)


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Total forward FLOPs (2.x ``paddle.flops``); ``custom_ops`` maps
    LayerClass -> fn(layer, in_shape, out_shape) for user layers."""
    from .utils.stats import summary as _s

    return _s(net, input_size, print_table=print_detail,
              custom_ops=custom_ops)["total_flops"]
# the submodule import rebinds the package attr 'dist' to the module;
# restore the function for paddle.dist parity
from .ops.linalg import dist  # noqa: E402,F811
from .framework import jit as _jit_mod
from .framework.jit import jit, to_static, TrainStep
from .framework.recompute import recompute, Recompute
from .framework.io import save, load
from .static_ import enable_static, disable_static
from .static_.program import program_guard, global_scope


def in_dynamic_mode():
    return not static.in_static_mode()
from .optim import regularizer
from .nn.param_attr import ParamAttr
from .utils import unique_name

bool = bool_  # paddle.bool

__all__ = [n for n in dir() if not n.startswith("_")]

# reader-creator combinators + batching (ref: paddle/reader, batch.py)
from . import reader  # noqa: E402
from . import compat  # noqa: E402
from .reader import batch  # noqa: E402

# 1.x tensor-API aliases (ref: python/paddle/tensor/math.py __all__)
div = ops.divide
elementwise_equal = ops.equal
elementwise_sum = ops.add_n


def create_tensor(dtype, name=None, persistable=False):
    """ref: tensor/creation.py create_tensor."""
    return ops.zeros([1], dtype=dtype)


__all__ += ["reader", "compat", "batch", "div", "elementwise_equal",
            "elementwise_sum", "create_tensor"]

# 2.x module surface (paddle.tensor/io/metric/optimizer/distributed/
# fleet/imperative/static/device/regularizer): attribute binds + the
# module-import spellings (import paddle_tpu.tensor, python -m
# paddle_tpu.distributed.launch, ...) — registered last so every
# implementation module they alias already exists.
from . import modules_compat as _modules_compat  # noqa: E402

_modules_compat.install(__name__)
