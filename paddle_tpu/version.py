"""paddle.version surface (ref: python/paddle/version.py, generated at
build time there; static here)."""
full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
istaged = True
commit = "tpu-native"
with_mkl = "OFF"


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")


def mkl():
    return with_mkl
