"""Windows scipy DLL-load sanity check.

ref: python/paddle/check_import_scipy.py — on Windows ('nt') a broken
scipy install manifests as a 'DLL load failed' ImportError at
``import scipy.io``; the reference probes it at package import and
re-raises with install guidance. On the TPU/Linux images this is a
no-op, but the name is part of the public surface.
"""


def check_import_scipy(OsName):
    if OsName != "nt":
        return
    try:
        import scipy.io  # noqa: F401
    except ImportError as e:
        if "DLL load failed" in str(e):
            raise ImportError(
                str(e) + "\nplease reinstall the Visual C++ Redistributable "
                "so scipy's compiled extensions can load"
            )
