"""Reader decorators (ref: python/paddle/reader/decorator.py + batch.py).

The classic composable reader-creator library: a "reader creator" is a
zero-arg callable returning an iterator of samples. These combinators
are host-side plumbing; device overlap is owned by io_/DataLoader and
the native prefetch ring (runtime/cc) — SURVEY §4b.
"""
from __future__ import annotations

import itertools
import queue as _queue
import random as _pyrandom
import threading

__all__ = [
    "batch", "map_readers", "buffered", "compose", "chain", "shuffle",
    "firstn", "cache", "xmap_readers", "multiprocess_reader",
    "ComposeNotAligned",
]


class ComposeNotAligned(ValueError):
    pass


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of ``batch_size`` (ref: batch.py)."""

    def impl():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return impl


def map_readers(func, *readers):
    """Element-wise map over zipped readers (ref: decorator.py)."""

    def impl():
        its = [r() for r in readers]
        for items in zip(*its):
            yield func(*items)

    return impl


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer (ref: decorator.py shuffle)."""

    def impl():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                _pyrandom.shuffle(buf)
                for s in buf:
                    yield s
                buf = []
        if buf:
            _pyrandom.shuffle(buf)
            for s in buf:
                yield s

    return impl


def chain(*readers):
    """Concatenate readers end-to-end (ref: decorator.py chain)."""

    def impl():
        return itertools.chain(*[r() for r in readers])

    return impl


def compose(*readers, **kwargs):
    """Zip readers into flattened tuples (ref: decorator.py compose).
    check_alignment=True raises ComposeNotAligned on length mismatch."""
    check_alignment = kwargs.pop("check_alignment", True)

    def to_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def impl():
        its = [r() for r in readers]
        sentinel = object()
        for items in itertools.zip_longest(*its, fillvalue=sentinel):
            # identity test: `in` would run numpy elementwise equality
            if any(i is sentinel for i in items):
                if check_alignment:
                    raise ComposeNotAligned(
                        "readers have different lengths")
                return
            yield sum((to_tuple(i) for i in items), ())

    return impl


def buffered(reader, size):
    """Prefetch up to ``size`` samples on a worker thread (ref:
    decorator.py buffered)."""

    def impl():
        q: _queue.Queue = _queue.Queue(maxsize=size)
        end = object()
        err = []

        def worker():
            try:
                for sample in reader():
                    q.put(sample)
            except BaseException as e:  # re-raised on the consumer side
                err.append(e)
            finally:
                q.put(end)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            s = q.get()
            if s is end:
                break
            yield s
        if err:
            raise err[0]

    return impl


def firstn(reader, n):
    """First n samples (ref: decorator.py firstn)."""

    def impl():
        return itertools.islice(reader(), n)

    return impl


def cache(reader):
    """Materialize once, replay from memory (ref: decorator.py cache)."""
    holder = {}

    def impl():
        if "data" not in holder:
            holder["data"] = list(reader())
        return iter(holder["data"])

    return impl


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker THREADS (ref:
    decorator.py xmap_readers; thread-based here — jax arrays and the
    GIL make processes a poor trade on the host side)."""

    def impl():
        in_q: _queue.Queue = _queue.Queue(buffer_size)
        out_q: _queue.Queue = _queue.Queue(buffer_size)
        end = object()
        err = []

        def feeder():
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
            except BaseException as e:
                err.append(e)
            finally:  # sentinels must flow even on failure, or we hang
                for _ in range(process_num):
                    in_q.put(end)

        def worker():
            try:
                while True:
                    item = in_q.get()
                    if item is end:
                        return
                    i, sample = item
                    out_q.put((i, mapper(sample)))
            except BaseException as e:
                err.append(e)
            finally:
                out_q.put(end)

        threading.Thread(target=feeder, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=worker, daemon=True).start()
        finished = 0
        pending = {}
        next_i = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if not order:
                yield item[1]
                continue
            pending[item[0]] = item[1]
            while next_i in pending:
                yield pending.pop(next_i)
                next_i += 1
        if err:
            raise err[0]
        # single FIFO: every item precedes its worker's end sentinel
        assert not pending, "xmap_readers lost ordered items"

    return impl


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave multiple readers via worker threads (ref:
    decorator.py multiprocess_reader; thread-backed for the same reason
    as xmap_readers)."""

    def impl():
        q: _queue.Queue = _queue.Queue(queue_size)
        end = object()
        err = []

        def worker(r):
            try:
                for sample in r():
                    q.put(sample)
            except BaseException as e:
                err.append(e)
            finally:
                q.put(end)

        for r in readers:
            threading.Thread(target=worker, args=(r,), daemon=True).start()
        finished = 0
        while finished < len(readers):
            s = q.get()
            if s is end:
                finished += 1
                continue
            yield s
        if err:
            raise err[0]

    return impl
