"""Recovery policies: what a detected fault turns into.

The reference reacts to faults operationally (fleet restarts the trainer,
``FLAGS_check_nan_inf`` aborts, the PS heartbeat re-elects); here the
policy is an object the guards (``resilience.guard``) interpret:

- ``on_nonfinite`` — what a NaN/Inf training step becomes:
  ``"raise"`` (abort, the reference flag's behavior), ``"skip_step"``
  (discard this step's updates and continue — the step contributes
  nothing, exactly as if its batch had been dropped), or ``"rollback"``
  (restore the last-good in-memory snapshot, taken every
  ``snapshot_every`` successful steps).
- bounded retry-with-backoff for transient compile/execute errors
  (``TransientError`` and injected ``TransientChaosError``): up to
  ``max_retries`` retries, sleeping ``backoff * backoff_factor**i``
  capped at ``max_backoff``, optionally spread by a seeded ``jitter``
  fraction (a pod's worth of workers retrying a shared service must
  not stampede it in lockstep) and bounded by a wall-clock
  ``deadline_s`` on ``retry_call`` (a retry loop must not outlive the
  preemption grace window it is racing).
- ``degrade_opt_level`` — when an optimized program
  (``optimize_level>0``) fails to compile/run but the unoptimized one
  succeeds, fall back to level 0 for the rest of the run instead of
  dying (a miscompiled pass must never kill a pod job).
"""
from __future__ import annotations

import time

from ..obs import metrics as _metrics
from .inject import TransientChaosError

__all__ = ["TransientError", "RecoveryPolicy", "retry_call",
           "NONFINITE_ACTIONS"]


class TransientError(RuntimeError):
    """A retryable infrastructure error (preempted RPC, flaky link).
    Raise (or subclass) this to opt an error into the retry path."""


NONFINITE_ACTIONS = ("raise", "skip_step", "rollback")


class RecoveryPolicy:
    def __init__(self, on_nonfinite="raise", max_retries=3, backoff=0.05,
                 backoff_factor=2.0, max_backoff=2.0, snapshot_every=1,
                 degrade_opt_level=True,
                 retryable=(TransientError, TransientChaosError),
                 sleep=None, jitter=0.0, jitter_seed=0):
        if on_nonfinite not in NONFINITE_ACTIONS:
            raise ValueError(
                f"on_nonfinite must be one of {NONFINITE_ACTIONS}, got "
                f"{on_nonfinite!r}")
        if not 0.0 <= float(jitter) <= 1.0:
            raise ValueError(f"jitter must be a fraction in [0, 1], got "
                             f"{jitter!r}")
        self.on_nonfinite = on_nonfinite
        self.max_retries = int(max_retries)
        self.backoff = float(backoff)
        self.backoff_factor = float(backoff_factor)
        self.max_backoff = float(max_backoff)
        self.snapshot_every = max(1, int(snapshot_every))
        self.degrade_opt_level = bool(degrade_opt_level)
        self.retryable = tuple(retryable)
        self.jitter = float(jitter)
        self.jitter_seed = int(jitter_seed)
        self._sleep = sleep if sleep is not None else time.sleep

    def backoff_for(self, attempt):
        """Deterministic backoff for retry ``attempt`` (0-based):
        exponential, capped at ``max_backoff``, then spread by a
        ±``jitter`` fraction drawn from ``RandomState(jitter_seed +
        attempt)``. Seeding per (seed, attempt) keeps tests replayable
        while workers seeded with their rank de-synchronize — jitter is
        applied AFTER the cap on purpose: clamping the spread back to
        ``max_backoff`` would re-synchronize exactly the long retries
        that stampede hardest."""
        base = min(self.backoff * self.backoff_factor ** attempt,
                   self.max_backoff)
        if self.jitter:
            import numpy as np

            u = np.random.RandomState(
                self.jitter_seed + attempt).uniform(-1.0, 1.0)
            base *= 1.0 + self.jitter * u
        return max(0.0, base)

    def __repr__(self):
        return (f"RecoveryPolicy(on_nonfinite={self.on_nonfinite!r}, "
                f"max_retries={self.max_retries}, "
                f"degrade_opt_level={self.degrade_opt_level})")


def retry_call(fn, policy=None, describe="", before_retry=None,
               deadline_s=None, clock=None):
    """Call ``fn()`` with the policy's bounded retry-with-backoff.

    Returns ``(result, attempts)`` where attempts >= 1. Non-retryable
    exceptions propagate immediately; a retryable one propagates only
    after the retry budget is exhausted. ``before_retry`` (if given)
    runs before each re-attempt — the hook where a guard restores state
    a failed attempt may have consumed (e.g. donated device buffers).

    ``deadline_s`` additionally bounds the WALL CLOCK spent retrying:
    when the next backoff sleep would land past ``deadline_s`` seconds
    from the first attempt, the retryable error propagates even with
    retry budget left — a retry loop racing a preemption grace window
    must fail fast enough to still checkpoint. ``clock`` (default
    ``time.monotonic``) is injectable so deadline tests are
    deterministic.
    """
    policy = policy or RecoveryPolicy()
    clock = clock if clock is not None else time.monotonic
    start = clock()
    attempt = 0
    while True:
        try:
            return fn(), attempt + 1
        except policy.retryable as err:
            if attempt >= policy.max_retries:
                raise
            delay = policy.backoff_for(attempt)
            if deadline_s is not None and \
                    (clock() - start) + delay > float(deadline_s):
                raise
            # the one chokepoint every guard's transient recovery passes
            # through — the process-wide resilience.retries counter lives
            # here (GuardStats keeps the per-guard view)
            _metrics.counter("resilience.retries").inc()
            from ..obs import journal as _journal

            if _journal.ACTIVE is not None:
                _journal.ACTIVE.event(
                    "resilience.retry", attempt=attempt + 1,
                    error=f"{type(err).__name__}: {err}")
            policy._sleep(delay)
            if before_retry is not None:
                before_retry()
            attempt += 1
