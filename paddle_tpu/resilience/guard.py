"""Guards: turn a detected fault into a recovered run.

Two wrappers, one per execution path:

- ``GuardedStep`` wraps a fused eager ``TrainStep`` (framework/jit.py).
  Detection is the step's existing on-device nonfinite flag
  (``TrainStep(check_nan=True)`` raises ``NanInfError`` after the step;
  no extra host sync is added). The policy then decides: re-raise, skip
  the step (restore the pre-step snapshot — the step contributes
  nothing, bitwise identical to a run that never saw that batch for
  RNG-free models), or roll back to the last-good snapshot.

- ``GuardedExecutor`` wraps the static ``Executor``. It adds bounded
  retry-with-backoff around compile/execute for transient errors,
  graceful degradation to ``optimize_level=0`` when the optimized
  program fails where the unoptimized one succeeds, and the same
  nonfinite policies over the fetched values (already host-side — no
  new sync) plus an optional on-device ``found_inf`` fetch.

Snapshots are in-memory device copies (``jnp.copy`` — async, donation-
safe: the executor/step donates its input buffers, so a bare reference
would be deleted). AMP interplay: restoring a static AMP program's state
EXCLUDES the ``@amp@*`` loss-scaling vars, so a skipped/rolled-back step
keeps the scale shrink the in-program machinery applied (otherwise the
same overflow repeats forever); for eager steps, pass the
``amp.GradScaler`` so ``notify_skip()`` advances its dynamic scale.
"""
from __future__ import annotations

import time
import warnings

import numpy as np

from ..obs import journal as _journal
from ..obs import metrics as _metrics
from ..utils.nan_guard import NanInfError
from . import inject
from .policy import RecoveryPolicy, retry_call

__all__ = ["GuardedStep", "GuardedExecutor", "GuardStats"]


class GuardStats:
    """Counters a guard accumulates (one instance per guard). ``inc``
    mirrors into the process-wide ``obs.metrics`` registry under
    ``resilience.<name>`` so fleet-level dashboards see every guard's
    recoveries without holding guard references — EXCEPT ``retries``,
    which ``policy.retry_call`` (the chokepoint every guard funnels
    through) already ticks globally per actual retry."""

    _COUNTERS = ("steps", "nonfinite", "skipped", "rollbacks", "retries",
                 "degraded")

    def __init__(self, owner=None):
        self.owner = owner      # which guard kind journal events cite
        self.steps = 0          # committed (good) steps
        self.nonfinite = 0      # nonfinite detections
        self.skipped = 0        # steps discarded by skip_step
        self.rollbacks = 0      # last-good restores
        self.retries = 0        # transient retries that happened
        self.degraded = 0       # optimize_level degradations

    def inc(self, name, n=1):
        setattr(self, name, getattr(self, name) + n)
        if n and name != "retries":
            _metrics.counter("resilience." + name).inc(n)
            # flight recorder: recoveries are journal events (committed
            # steps are step records, not events — they'd drown the
            # log). `source` tells the journal WHICH guard recovered:
            # only the static guard's skips reclassify an executor step
            if name != "steps" and _journal.ACTIVE is not None:
                _journal.ACTIVE.event("resilience." + name,
                                      source=self.owner)

    def as_dict(self):
        return {k: getattr(self, k) for k in self._COUNTERS}

    def __repr__(self):
        body = ", ".join(f"{k}={getattr(self, k)}" for k in self._COUNTERS)
        return f"GuardStats({body})"


def _copy_tree(obj):
    import jax.numpy as jnp

    if isinstance(obj, dict):
        return {k: _copy_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_copy_tree(v) for v in obj)
    if hasattr(obj, "dtype") and hasattr(obj, "shape"):
        return jnp.copy(obj)  # device copy, async, survives donation
    return obj


def _nonfinite_fetches(fetches):
    """Host-side scan of fetched values (they are already on the host —
    this adds no device sync)."""
    for f in fetches:
        a = np.asarray(getattr(f, "_data", f))
        if a.dtype.kind == "f" and not np.isfinite(a).all():
            return True
    return False


def _nonfinite_state(scope, names):
    """On-device finite-check of committed persistables, fused into ONE
    scalar sync (per-array syncs would serialize N device round-trips a
    step). Catches faults the fetches can't show: the executable's
    fetched values are computed from PRE-update state, so a fault that
    first materializes in the committed update (NaN learning rate, grad
    overflow under a finite loss) would otherwise only surface one step
    later — after the guard has already snapshotted the poisoned state
    as 'good'."""
    import jax.numpy as jnp

    flags = []
    for n in names:
        a = scope.find_var(n)
        if a is not None and hasattr(a, "dtype") and \
                np.issubdtype(np.dtype(a.dtype), np.floating):
            flags.append(jnp.any(~jnp.isfinite(a)))
    return bool(jnp.stack(flags).any()) if flags else False


class GuardedStep:
    """Policy wrapper over a fused eager ``TrainStep``.

    >>> step = pt.TrainStep(model, opt, loss_fn, check_nan=True)
    >>> guarded = GuardedStep(step, RecoveryPolicy(on_nonfinite="skip_step"))
    >>> loss = guarded(x, y)      # None when the step was discarded

    ``scaler`` (optional ``amp.GradScaler``): a guard-discarded step
    advances the scaler's dynamic state machine via ``notify_skip()``.
    This is BOOKKEEPING consistency, not training math: a
    ``TrainStep(check_nan=True)`` without an in-step scaler does no loss
    scaling, so the shrink changes nothing inside the step — it keeps a
    GradScaler used elsewhere (eager protocol runs, checkpointed scaler
    state) recording the same skip/overflow history the guard observed.
    A TrainStep built WITH a scaler never reaches the guard's nonfinite
    path at all (its in-graph found_inf already freezes the update and
    shrinks the scale); the guard then only adds retry/stats.
    """

    def __init__(self, step, policy=None, scaler=None):
        self.step = step
        self.policy = policy or RecoveryPolicy()
        self.scaler = scaler
        self.stats = GuardStats(owner="guarded_step")
        self._last_good = None
        if self.policy.on_nonfinite != "raise" and not step.check_nan \
                and step.scaler is None:
            raise ValueError(
                "GuardedStep needs the step's on-device nonfinite flag: "
                "construct TrainStep(check_nan=True) (or attach a loss "
                "scaler, whose in-graph found_inf already skips updates)")

    # -- snapshot / restore of the step's entire mutable state ---------------
    def _take_snapshot(self):
        st, opt = self.step, self.step.optimizer
        return {
            "params": [_copy_tree(p._data) for p in st._trainable],
            "buffers": [_copy_tree(b._data) for b in st._buffers],
            "opt": {p.name: _copy_tree(opt._accumulators[p.name])
                    for p in st._trainable},
            "scaler": _copy_tree(st._scaler_state),
            "gstep": opt._global_step,
        }

    def _restore(self, snap):
        st, opt = self.step, self.step.optimizer
        # install copies so the snapshot survives a later donation of
        # the restored buffers (rollback may restore the same snapshot
        # more than once)
        for p, a in zip(st._trainable, snap["params"]):
            p._data = _copy_tree(a)
        for b, a in zip(st._buffers, snap["buffers"]):
            b._data = _copy_tree(a)
        for name, s in snap["opt"].items():
            opt._accumulators[name] = _copy_tree(s)
        st._scaler_state = _copy_tree(snap["scaler"])
        opt._global_step = snap["gstep"]

    def __call__(self, *batch):
        pol = self.policy
        if inject.ACTIVE:
            batch = inject.fire("nan_feed", list(batch))
        t0 = time.perf_counter()
        # snapshot EVERY call: the fused step donates its param/buffer/
        # opt-state buffers, so a failed execution that a user opted
        # into retry (policy.retryable) leaves deleted buffers behind —
        # each re-attempt must restore first. skip_step reuses the same
        # snapshot, and rollback falls back to it before the first
        # verified-good snapshot exists.
        pre = self._take_snapshot()

        def attempt():
            if inject.ACTIVE:  # same transient-infrastructure chaos
                inject.fire("transient_execute")  # point the static
            return self.step(*batch)  # Executor.run exposes

        try:
            loss, attempts = retry_call(attempt, pol,
                                        before_retry=lambda:
                                        self._restore(pre))
        except NanInfError:
            self.stats.inc("nonfinite")
            if pol.on_nonfinite == "raise":
                raise
            if pol.on_nonfinite == "skip_step":
                self._restore(pre)
                self.stats.inc("skipped")
            else:
                self._restore(self._last_good if self._last_good
                              else pre)
                self.stats.inc("rollbacks")
            if self.scaler is not None:
                self.scaler.notify_skip()
            if _journal.ACTIVE is not None:
                _journal.ACTIVE.record_step(
                    loss=None, step_ms=(time.perf_counter() - t0) * 1e3,
                    skipped=True, nonfinite=True, source="guarded_step")
            return None
        self.stats.inc("retries", attempts - 1)
        self.stats.inc("steps")
        if pol.on_nonfinite == "rollback" and \
                self.stats.steps % pol.snapshot_every == 0:
            self._last_good = self._take_snapshot()
        if _journal.ACTIVE is not None:
            # journaling an eager step reads the scalar loss to the host
            # (one scalar sync — the standard cost of logging a loss;
            # inactive journal = the single None check above)
            try:
                lv = float(np.asarray(getattr(loss, "_data", loss)))
            except (TypeError, ValueError):
                lv = None
            _journal.ACTIVE.record_step(
                loss=lv, step_ms=(time.perf_counter() - t0) * 1e3,
                source="guarded_step")
        return loss


class GuardedExecutor:
    """Policy wrapper over the static ``Executor``.

    >>> gexe = GuardedExecutor(policy=RecoveryPolicy(on_nonfinite="skip_step"))
    >>> gexe.run(startup)
    >>> out = gexe.run(prog, feed=..., fetch_list=[loss])  # None if skipped

    ``found_inf_var``: name of an on-device bool var (e.g. the static AMP
    pass's ``"@amp@found_inf"``) fetched alongside the user's fetch_list
    for detection; without it, detection falls back to a host-side scan
    of the fetched arrays. The scan cannot tell a fault from a fetch
    that LEGITIMATELY contains inf (an additive attention mask, a
    log-prob of an impossible class) — fetching one of those under a
    skip/rollback policy would discard every step. For such programs
    pass ``found_inf_var`` (authoritative, scan suppressed) or
    ``scan_fetches=False``.

    ``scan_state`` (default True, suppressed by ``found_inf_var``): also
    finite-check the step's COMMITTED persistables on device. The
    fetched values are computed from pre-update state, so without this a
    fault that first lands in the committed update (a NaN learning rate,
    a grad overflow under a finite loss) is seen one step late — after
    the poisoned weights were snapshotted as "good", which would make
    skip/rollback restore poison forever. Costs one small device sync
    per persistable per run; ``scan_state=False`` opts out.

    Every guarded run of a non-empty program snapshots the persistable
    state first (device copies): retry and degrade re-attempts restore
    it before re-running, because a failed execution may already have
    consumed the donated input buffers (and a post-commit failure must
    not double-apply the update).
    """

    def __init__(self, executor=None, policy=None, found_inf_var=None,
                 scan_fetches=True, scan_state=True):
        if executor is None:
            from ..static_.executor import Executor

            executor = Executor()
        self.executor = executor
        self.policy = policy or RecoveryPolicy()
        self.found_inf_var = found_inf_var
        self.scan_fetches = bool(scan_fetches)
        self.scan_state = bool(scan_state)
        self.stats = GuardStats(owner="guarded_executor")
        self._last_good = None
        self._degraded = False

    # -- persistable-state snapshots -----------------------------------------
    @staticmethod
    def _persist_names(program):
        """ALL persistables, including @amp@* loss-scaling state: the
        retry/degrade restore must reinstate every donated buffer a
        failed attempt consumed. The nonfinite-policy restore filters
        @amp@* back OUT (see _restore's keep_amp) so a skipped step
        retains the loss-scale shrink the in-program machinery applied
        — or the same overflow would just repeat."""
        base = getattr(program, "_program", program)
        return [v.name for v in base.global_block.vars.values()
                if v.persistable]

    def _take_snapshot(self, names, scope):
        return {n: _copy_tree(scope.find_var(n)) for n in names
                if scope.find_var(n) is not None}

    def _restore(self, snap, scope, keep_amp=False):
        """``keep_amp``: leave the live @amp@* loss-scaling state in
        place (nonfinite skip/rollback — the in-program scale shrink
        must survive the restore). The retry path restores EVERYTHING:
        a failed attempt consumed the donated @amp@ buffers too."""
        for n, a in snap.items():
            if keep_amp and n.startswith("@amp@"):
                continue
            scope.set(n, _copy_tree(a))

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            optimize_level=None, **kw):
        from ..static_.program import default_main_program, global_scope

        pol = self.policy
        if program is None:
            program = default_main_program()
        scope = scope or global_scope()
        fetch_list = list(fetch_list or [])
        n_user_fetch = len(fetch_list)
        base = getattr(program, "_program", program)
        if self.found_inf_var is not None and \
                base.global_block.has_var(self.found_inf_var):
            fetch_list.append(self.found_inf_var)

        level = 0 if self._degraded else optimize_level
        guard_state = bool(base.global_block.ops)
        names = self._persist_names(program) if guard_state else []
        # snapshot EVERY guarded run (not just non-raise policies): a
        # failed execution may have consumed the donated input buffers,
        # so any retry/degrade re-attempt must first restore the state
        pre = self._take_snapshot(names, scope) if guard_state else None
        # NOTE: _last_good is only ever seeded from a committed state
        # that passed the scan (below); a pre-run snapshot taken before
        # the scope is populated (e.g. through a startup program) could
        # be EMPTY, and restoring {} on rollback would recover nothing

        def restore_pre():
            if pre is not None:
                self._restore(pre, scope)

        def attempt(lvl):
            def call():
                return self.executor.run(
                    program, feed=feed, fetch_list=fetch_list, scope=scope,
                    optimize_level=lvl, **kw)
            return retry_call(call, pol, before_retry=restore_pre)

        try:
            fetches, attempts = attempt(level)
        except pol.retryable:
            raise  # transient retry budget exhausted: a real outage
        except Exception as err:
            resolved = level if level is not None else \
                getattr(self.executor, "optimize_level", 1)
            if not (pol.degrade_opt_level and int(resolved) != 0):
                raise
            restore_pre()  # the failed optimized attempt may have
            try:            # consumed buffers or half-committed updates
                fetches, attempts = attempt(0)
            except Exception:
                raise err  # level 0 fails too: the pipeline wasn't at fault
            warnings.warn(
                f"optimized program (optimize_level={resolved}) failed "
                f"({type(err).__name__}: {err}) but optimize_level=0 "
                "succeeds; degrading this GuardedExecutor to level 0 for "
                "subsequent runs", RuntimeWarning)
            self._degraded = True
            self.stats.inc("degraded")
        self.stats.inc("retries", attempts - 1)

        if len(fetch_list) > n_user_fetch:  # the appended found_inf var
            # the on-device flag is authoritative: a False verdict must
            # NOT be second-guessed by the host-side scan, or fetches
            # that legitimately contain inf (masks, log-probs) would
            # make every step read as faulty
            found_inf = bool(np.asarray(
                getattr(fetches[-1], "_data", fetches[-1])))
            fetches = fetches[:n_user_fetch]
        else:
            found_inf = self.scan_fetches and _nonfinite_fetches(fetches)
            if not found_inf and self.scan_state and guard_state:
                found_inf = _nonfinite_state(scope, names)

        if found_inf:
            self.stats.inc("nonfinite")
            if pol.on_nonfinite == "raise":
                raise NanInfError(
                    "nonfinite value in fetched results or committed "
                    "state (policy: raise); re-run under "
                    "RecoveryPolicy(on_nonfinite='skip_step' or "
                    "'rollback') to recover instead")
            if pol.on_nonfinite == "skip_step":
                self._restore(pre, scope, keep_amp=True)
                self.stats.inc("skipped")
            else:
                # no verified-good snapshot yet (first steps, or coarse
                # cadence): this run's pre-state IS the last good state —
                # it is the committed state of the previous run, which
                # passed the scan
                self._restore(self._last_good if self._last_good
                              else pre, scope, keep_amp=True)
                self.stats.inc("rollbacks")
            return None
        if guard_state:  # an empty (startup) program is not a step
            self.stats.inc("steps")
            if pol.on_nonfinite == "rollback" and \
                    self.stats.steps % pol.snapshot_every == 0:
                self._last_good = self._take_snapshot(names, scope)
        return fetches
