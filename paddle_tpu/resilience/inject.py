"""Deterministic fault injection: named chaos points.

The reference stack is hardened by operational practice (the fleet HA
utilities, ``FLAGS_check_nan_inf``, checkpoint hygiene in
``fluid/incubate/checkpoint``); none of it is *testable* without a way to
make the faults happen on demand. This module is that way: a registry of
seed-driven injectors, one per fault class the runtime can hit in a long
pod job —

- ``nan_op``        corrupt an eager op's output to NaN/Inf (dispatch hook)
- ``nan_feed``      corrupt one element of a fed batch at step N
- ``transient_compile`` / ``transient_execute``
                    raise a retryable error from ``Executor._compile`` /
                    the compiled step's invocation, N times then heal
- ``opt_compile_fail``  non-transient failure only when ``optimize_level>0``
                    (exercises graceful degradation to level 0)
- ``ckpt_crash``    die between writing a checkpoint and publishing it
                    (leaves an orphaned ``.tmp_ckpt_*`` dir)
- ``ckpt_truncate`` / ``ckpt_bitflip``
                    corrupt a published checkpoint file
- ``ckpt_slow``     stall the checkpoint writer between writing files and
                    the atomic publish (the window a killed writer leaves
                    only a tmp orphan, and the window an async save must
                    keep off the step path)
- ``loader_worker`` kill a DataLoader prefetch worker thread mid-batch
- ``worker_kill`` / ``worker_hang`` / ``preempt_signal``
                    gang-level faults fired from the worker's
                    step-boundary hook (``resilience.elastic
                    .fire_step_chaos``): hard process death, silent
                    no-progress hang (heartbeats stop; the supervisor's
                    watchdog must catch it), and a SIGTERM preemption
                    notice. These support global-step keyed firing
                    (``at_step=N``) so a relaunched worker that resumed
                    PAST the fault step does not re-fire, and ``rank=R``
                    gating so one env spec can target one gang member.

Activation is explicit and scoped: the ``chaos("point", ...)`` context
manager, or the ``PADDLE_TPU_CHAOS`` env var
(``"point:key=val,key=val;point2"``) for whole-process runs such as
``tools/chaos_run.py``. When nothing is active, ``ACTIVE`` is an empty
dict and every production hook is a single ``if not ACTIVE`` — no device
sync, no allocation, nothing on the hot path.

Determinism: an injector fires on hit indices ``at .. at+times-1`` of its
chaos point (hits are counted per activation, under a lock) and any
randomness (which element / bit to flip) comes from
``np.random.RandomState(seed + hit)``. The same (at, times, seed) config
always breaks the same run the same way — a chaos test failure replays.
"""
from __future__ import annotations

import contextlib
import os
import signal as _signal
import threading
import time

import numpy as np

__all__ = [
    "ChaosError", "TransientChaosError", "WorkerCrashChaos",
    "SimulatedCrashError", "Injector", "INJECTORS", "ACTIVE",
    "register_injector", "chaos", "fire", "clear", "install_from_env",
]


class ChaosError(RuntimeError):
    """Base class for every injected fault."""


class TransientChaosError(ChaosError):
    """Injected fault that models a *retryable* infrastructure error
    (preempted compile RPC, flaky ICI link): recovery layers treat it
    like ``resilience.policy.TransientError``."""


class WorkerCrashChaos(ChaosError):
    """Injected fault that kills a DataLoader worker thread (escapes the
    per-batch error capture on purpose)."""


class SimulatedCrashError(ChaosError):
    """The process 'died' at the injection point (e.g. mid-checkpoint)."""


INJECTORS: dict[str, type] = {}  # point name -> injector class
ACTIVE: dict[str, "Injector"] = {}  # point name -> live injector


def register_injector(name):
    def deco(cls):
        cls.point = name
        INJECTORS[name] = cls
        return cls
    return deco


class Injector:
    """One configured fault. Fires on hit indices at..at+times-1."""

    point = None

    def __init__(self, at=1, times=1, seed=0, **cfg):
        self.at = int(at)
        self.times = int(times)
        self.seed = int(seed)
        self.cfg = cfg
        self.hits = 0
        self.fired = 0
        self._lock = threading.Lock()

    def should_fire(self):
        with self._lock:
            self.hits += 1
            if self.hits >= self.at and self.fired < self.times:
                self.fired += 1
                return True
            return False

    def _eligible(self):
        """Count the hit but DON'T consume firing budget yet — for
        injectors whose fault may turn out inapplicable at this hit
        (see ``_commit_fire``). The window stays open until ``times``
        faults actually landed."""
        with self._lock:
            self.hits += 1
            return self.hits >= self.at and self.fired < self.times

    def _commit_fire(self):
        with self._lock:
            self.fired += 1

    def _rng(self):
        # per-firing stream: firing twice corrupts two different elements
        return np.random.RandomState(self.seed + self.fired)

    def fire(self, value=None, **ctx):  # pragma: no cover - overridden
        return value

    def __repr__(self):
        return (f"{type(self).__name__}(at={self.at}, times={self.times}, "
                f"seed={self.seed}, hits={self.hits}, fired={self.fired})")


def fire(point, value=None, **ctx):
    """Production-side hook: pass ``value`` through the active injector
    for ``point`` (which may corrupt it or raise), or return it untouched.
    Callers guard with ``if ACTIVE:`` so the disabled path is one empty-
    dict truthiness test."""
    inj = ACTIVE.get(point)
    if inj is None:
        return value
    return inj.fire(value, **ctx)


# -- injectors ---------------------------------------------------------------


def _bad_value(kind):
    return np.inf if str(kind) == "inf" else np.nan


@register_injector("nan_feed")
class NanFeedInjector(Injector):
    """Corrupt one element of one fed array (dict feed or batch list).

    cfg: ``var`` — feed name (dict) or positional index (list); defaults
    to the first sorted name / index 0. ``kind`` — "nan" (default) or
    "inf". The corrupted container is a copy; the caller's arrays are
    never mutated.
    """

    @staticmethod
    def _corruptible(arr):
        a = np.asarray(arr)
        return a.dtype.kind == "f" and a.size > 0

    def fire(self, value=None, **ctx):
        if value is None or not self._eligible():
            return value
        # locate the target FIRST: a hit whose feed has no corruptible
        # target (name typo, int-only feed, empty batch) must not consume
        # the firing budget — otherwise a drill can 'recover' from a
        # fault that was never injected
        if isinstance(value, dict):
            name = self.cfg.get("var")
            if name is None:
                # default target is a USER feed: '@'-prefixed names are
                # executor internals ('@lr'), and '@' sorts first
                users = sorted(n for n in value if not n.startswith("@"))
                name = users[0] if users else None
            if name not in value or not self._corruptible(value[name]):
                return value
            key = name
            container = dict(value)
        else:
            idx = int(self.cfg.get("var", 0))
            if not (0 <= idx < len(value)) or \
                    not self._corruptible(value[idx]):
                return list(value)
            key = idx
            container = list(value)
        self._commit_fire()
        kind = _bad_value(self.cfg.get("kind", "nan"))
        a = np.asarray(container[key]).copy()
        a.ravel()[int(self._rng().randint(a.size))] = kind
        container[key] = a
        return container


@register_injector("nan_op")
class NanOpInjector(Injector):
    """Corrupt an eager op's first floating output (dispatch-level, the
    chaos twin of ``FLAGS_check_nan_inf``'s detection point).

    cfg: ``op`` — only count hits on this op type (default: every op);
    ``kind`` — "nan"/"inf".
    """

    def fire(self, value=None, op_type=None, **ctx):
        want = self.cfg.get("op")
        if want is not None and op_type != want:
            return value
        if not self._eligible():
            return value
        outs = list(value)
        target = next(
            (i for i, o in enumerate(outs)
             if hasattr(o, "dtype") and np.issubdtype(o.dtype, np.floating)
             and getattr(o, "size", 0)), None)
        if target is None:
            return value  # no float output: budget not consumed
        self._commit_fire()
        import jax.numpy as jnp

        o = outs[target]
        flat = jnp.ravel(o)
        idx = int(self._rng().randint(flat.shape[0]))
        bad = flat.at[idx].set(_bad_value(self.cfg.get("kind", "nan")))
        outs[target] = jnp.reshape(bad, o.shape)
        return tuple(outs)


@register_injector("transient_compile")
class TransientCompileInjector(Injector):
    """Executor._compile raises a retryable error on the firing hits."""

    def fire(self, value=None, **ctx):
        if self.should_fire():
            raise TransientChaosError(
                f"injected transient compile failure "
                f"(hit {self.hits}, firing {self.fired}/{self.times})")
        return value


@register_injector("transient_execute")
class TransientExecuteInjector(Injector):
    """The compiled step's invocation raises a retryable error."""

    def fire(self, value=None, **ctx):
        if self.should_fire():
            raise TransientChaosError(
                f"injected transient execute failure "
                f"(hit {self.hits}, firing {self.fired}/{self.times})")
        return value


@register_injector("opt_compile_fail")
class OptCompileFailInjector(Injector):
    """Non-transient compile failure ONLY under optimization
    (optimize_level > 0) — the scenario where degrading to the
    unoptimized program recovers the run."""

    def fire(self, value=None, optimize_level=0, **ctx):
        if int(optimize_level) <= 0:
            return value
        if self.should_fire():
            raise ChaosError(
                f"injected optimizer-pipeline failure at optimize_level="
                f"{optimize_level}")
        return value


@register_injector("ckpt_crash")
class CkptCrashInjector(Injector):
    """Die after writing checkpoint files but BEFORE the atomic publish:
    the orphaned ``.tmp_ckpt_*`` dir is exactly what a real mid-save
    crash leaves behind."""

    def fire(self, value=None, **ctx):
        if self.should_fire():
            raise SimulatedCrashError(
                f"simulated crash before checkpoint publish (tmp={value})")
        return value


class _CkptFileCorruptor(Injector):
    target_default = "model.pdparams"

    def _target(self, ckpt_dir):
        name = self.cfg.get("file", self.target_default)
        path = os.path.join(ckpt_dir, name)
        if not os.path.exists(path):
            for fn in sorted(os.listdir(ckpt_dir)):
                if fn != "manifest.json":
                    return os.path.join(ckpt_dir, fn)
        return path

    def corrupt(self, path, rng):  # pragma: no cover - overridden
        raise NotImplementedError

    def fire(self, value=None, **ctx):
        if value is None or not self.should_fire():
            return value
        path = self._target(value)
        if path and os.path.exists(path):
            self.corrupt(path, self._rng())
        return value


@register_injector("ckpt_truncate")
class CkptTruncateInjector(_CkptFileCorruptor):
    """Truncate a published checkpoint file to ``fraction`` of its size
    (default 0.5) — a torn write / out-of-quota artifact."""

    def corrupt(self, path, rng):
        size = os.path.getsize(path)
        frac = float(self.cfg.get("fraction", 0.5))
        with open(path, "r+b") as f:
            f.truncate(max(0, int(size * frac)))


@register_injector("ckpt_bitflip")
class CkptBitflipInjector(_CkptFileCorruptor):
    """Flip one seeded bit of a published checkpoint file — silent media
    corruption that only a checksum can catch."""

    def corrupt(self, path, rng):
        size = os.path.getsize(path)
        if size == 0:
            return
        off = int(rng.randint(size))
        bit = 1 << int(rng.randint(8))
        with open(path, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ bit]))


@register_injector("ckpt_slow")
class CkptSlowInjector(Injector):
    """Stall the checkpoint writer for ``seconds`` (default 0.5) between
    writing the checkpoint files and the atomic publish — a slow/remote
    filesystem. Under ``save_checkpoint(async_=True)`` the stall runs on
    the background writer thread, which is exactly what the
    never-blocks-the-step-loop tests assert; a process killed inside the
    stall leaves only the ``.tmp_ckpt_*`` orphan (publish never ran)."""

    def fire(self, value=None, **ctx):
        if self.should_fire():
            time.sleep(float(self.cfg.get("seconds", 0.5)))
        return value


class _WorkerFaultInjector(Injector):
    """Base for gang-level faults fired from the worker training loop's
    step boundary (``resilience.elastic.fire_step_chaos``).

    Two firing modes:

    - ``at_step=N`` — fire when the GLOBAL step equals N. Because a
      relaunched worker resumes from a checkpoint at/after the fault
      step, the same ``PADDLE_TPU_CHAOS`` spec inherited across
      restarts fires exactly once per drill instead of re-killing every
      incarnation.
    - default hit-based ``at``/``times`` — hits are counted per process
      activation, so EVERY incarnation re-fires: the restart-budget-
      exhaustion drill.

    ``rank=R`` additionally gates either mode to one gang member."""

    def _worker_applies(self, step=None, rank=None):
        want_rank = self.cfg.get("rank")
        if want_rank is not None and rank is not None and \
                int(want_rank) != int(rank):
            return False
        at_step = self.cfg.get("at_step")
        if at_step is not None:
            if step is None or int(step) != int(at_step):
                return False
            with self._lock:
                self.hits += 1
                if self.fired >= self.times:
                    return False
                self.fired += 1
                return True
        return self.should_fire()


@register_injector("worker_kill")
class WorkerKillInjector(_WorkerFaultInjector):
    """Hard-kill the calling worker process via ``os._exit`` — no
    cleanup, no journal flush, no atexit: exactly what machine loss
    looks like to the gang supervisor. cfg: ``code`` (exit code,
    default 1), plus ``at_step``/``rank`` gating."""

    def fire(self, value=None, step=None, rank=None, **ctx):
        if self._worker_applies(step, rank):
            os._exit(int(self.cfg.get("code", 1)))
        return value


@register_injector("worker_hang")
class WorkerHangInjector(_WorkerFaultInjector):
    """Stop making progress WITHOUT dying: the main thread spins in
    sleep, so heartbeats stop but the process stays alive — only the
    supervisor's heartbeat watchdog can detect and kill it (a plain
    ``wait()`` never returns). cfg: ``seconds`` bounds the hang for
    in-process unit tests; unset hangs until killed."""

    def fire(self, value=None, step=None, rank=None, **ctx):
        if self._worker_applies(step, rank):
            seconds = self.cfg.get("seconds")
            if seconds is not None:
                time.sleep(float(seconds))
            else:  # hang until the watchdog kills us; SIGTERM only sets
                while True:  # the graceful flag, which we never check
                    time.sleep(1.0)
        return value


@register_injector("preempt_signal")
class PreemptSignalInjector(_WorkerFaultInjector):
    """Deliver SIGTERM to the calling process — the maintenance/
    preemption notice a TPU VM gets. With
    ``resilience.graceful_shutdown()`` installed the worker checkpoints
    at the next step boundary and exits ``PREEMPTED_EXIT_CODE``
    (restart-eligible, budget-free); without a handler the default
    disposition kills the process (128+15)."""

    def fire(self, value=None, step=None, rank=None, **ctx):
        if self._worker_applies(step, rank):
            os.kill(os.getpid(), _signal.SIGTERM)
        return value


@register_injector("replica_kill")
class ReplicaKillInjector(_WorkerFaultInjector):
    """Hard-kill a SERVE replica process mid-flight via ``os._exit`` —
    the machine-loss fault for the serving fleet. Fired from
    ``ServeEngine.step()``'s boundary hook with the engine's serve-step
    count and replica id, so ``at=N`` means "die inside serve step N"
    (typically mid-decode) and ``rank=R`` targets one replica of a
    ``serving.fleet.ReplicaPool``. The router's drill asserts the
    stranded requests requeue in arrival order and finish
    oracle-identical on the survivors while the relaunched replica
    hydrates AOT-warm. cfg: ``code`` (exit code, default 1)."""

    def fire(self, value=None, step=None, rank=None, **ctx):
        if self._worker_applies(step, rank):
            os._exit(int(self.cfg.get("code", 1)))
        return value


@register_injector("loader_worker")
class LoaderWorkerInjector(Injector):
    """Kill a DataLoader prefetch worker thread (the exception escapes
    the per-batch error capture; the prefetcher's restart budget is the
    recovery under test)."""

    def fire(self, value=None, **ctx):
        if self.should_fire():
            raise WorkerCrashChaos(
                f"injected loader worker crash (hit {self.hits})")
        return value


# -- activation --------------------------------------------------------------


def _journal_event(kind, **fields):
    """Chaos (de)activation lands in the run journal — a drill must be
    distinguishable from a real fault in the flight record. Imported
    lazily: inject loads very early and must not pull obs eagerly."""
    try:
        from ..obs import journal as _journal
    except Exception:
        return
    if _journal.ACTIVE is not None:
        _journal.ACTIVE.event(kind, **fields)


def _sync_hooks():
    """Propagate ACTIVE into runtimes that need a push-style hook (the
    eager dispatcher can't afford a cross-module dict probe per op)."""
    from ..core import dispatch

    if "nan_op" in ACTIVE:
        dispatch.set_chaos_op_hook(
            lambda name, outs: fire("nan_op", outs, op_type=name))
    else:
        dispatch.set_chaos_op_hook(None)


@contextlib.contextmanager
def chaos(point, **cfg):
    """Activate one chaos point for the duration of the block.

    >>> with chaos("transient_compile", times=2):
    ...     guarded.run(prog, feed=..., fetch_list=[loss])
    """
    if point not in INJECTORS:
        raise KeyError(
            f"unknown chaos point '{point}' (registered: "
            f"{sorted(INJECTORS)})")
    inj = INJECTORS[point](**cfg)
    prev = ACTIVE.get(point)
    ACTIVE[point] = inj
    _sync_hooks()
    _journal_event("chaos.activate", point=point, cfg=dict(
        at=inj.at, times=inj.times, seed=inj.seed, **inj.cfg))
    try:
        yield inj
    finally:
        if prev is None:
            ACTIVE.pop(point, None)
        else:
            ACTIVE[point] = prev
        _sync_hooks()
        _journal_event("chaos.deactivate", point=point, fired=inj.fired)


def clear():
    """Deactivate every chaos point."""
    ACTIVE.clear()
    _sync_hooks()


def install_from_env(env=None):
    """Activate chaos points from ``PADDLE_TPU_CHAOS``.

    Format (shared ``utils.envspec`` grammar):
    ``"point:key=val,key=val;point2"`` — e.g.
    ``PADDLE_TPU_CHAOS="transient_compile:times=2;nan_feed:at=3,seed=1"``.
    Returns the list of activated points.
    """
    from ..utils.envspec import parse_spec

    spec = env if env is not None else os.environ.get("PADDLE_TPU_CHAOS", "")
    out = []
    for point, cfg in parse_spec(spec):
        if point not in INJECTORS:
            raise KeyError(
                f"PADDLE_TPU_CHAOS names unknown point '{point}' "
                f"(registered: {sorted(INJECTORS)})")
        ACTIVE[point] = INJECTORS[point](**cfg)
        out.append(point)
    if out:
        _sync_hooks()
        for point in out:
            _journal_event("chaos.activate", point=point, source="env")
    return out


if os.environ.get("PADDLE_TPU_CHAOS"):
    install_from_env()
