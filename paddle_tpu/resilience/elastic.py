"""Elastic gang supervision: preemption-aware multi-process training.

Everything below PR 2 recovers faults *inside* one process; at pod
scale (PAPERS.md, arXiv 1909.09756) the routine fault is a whole
machine: a worker crashes, hangs, or gets a preemption notice, and the
old ``dist/launch.py`` spawn-and-wait either orphaned the survivors or
garbled the exit code. This module promotes the resilience layer to
whole-gang elasticity (ROADMAP item 4; the reference's
``incubate/fleet`` elastic + HA utilities):

- workers write heartbeat files (:class:`Heartbeat`, path handed down
  via ``PADDLE_TPU_HEARTBEAT_FILE``) from their TRAINING LOOP — not a
  background thread, so a deadlocked step stops the beacon and becomes
  visible;
- workers install :func:`graceful_shutdown`, which turns SIGTERM/SIGINT
  into "checkpoint at the next step boundary, then exit
  ``PREEMPTED_EXIT_CODE``" — the supervisor treats that code as
  restart-eligible WITHOUT consuming the crash budget;
- the :class:`GangSupervisor` spawns the gang, watches exits AND
  heartbeat staleness (a hung worker is SIGKILLed, never waited on
  forever), tears the WHOLE gang down on any failure (no orphans), and
  relaunches it — workers resume themselves from the newest intact
  checkpoint via ``framework.io.load_checkpoint``'s manifest fallback —
  under a bounded restart budget with seeded, jittered exponential
  backoff;
- :func:`fire_step_chaos` is the worker-side hook the ``worker_kill`` /
  ``worker_hang`` / ``preempt_signal`` injectors fire from, so every
  path above is drillable deterministically on CPU
  (``tools/elastic_run.py``).

Restarts/preemptions/watchdog kills land as ``resilience.*`` metrics
and ``elastic.*`` journal events (``tools/run_report.py`` renders them
as an elastic summary next to goodput).
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

from ..obs import metrics as _metrics
from . import inject as _inject
from .policy import RecoveryPolicy as _RecoveryPolicy

__all__ = [
    "PREEMPTED_EXIT_CODE", "HEARTBEAT_ENV", "ATTEMPT_ENV",
    "ElasticBudgetError", "Heartbeat", "GracefulShutdown",
    "graceful_shutdown", "ProgramStateAdapter", "GangSupervisor",
    "ReplicaSupervisor",
    "fire_step_chaos", "newest_intact_step", "normalize_exit_code",
]

# EX_TEMPFAIL: "transient failure, retry" — distinct from every code a
# crash produces, and stable across restarts of this module
PREEMPTED_EXIT_CODE = 75
HEARTBEAT_ENV = "PADDLE_TPU_HEARTBEAT_FILE"
ATTEMPT_ENV = "PADDLE_TPU_ELASTIC_ATTEMPT"

_M_RESTARTS = _metrics.counter("resilience.restarts")
_M_PREEMPTIONS = _metrics.counter("resilience.preemptions")
_M_WATCHDOG = _metrics.counter("resilience.watchdog_kills")
_M_PREEMPT_SIGNALS = _metrics.counter("resilience.preempt_signals")
_M_RESUME_MS = _metrics.histogram("resilience.resume_ms",
                                  buckets=_metrics.WIDE_MS_BUCKETS)


def normalize_exit_code(code):
    """``Popen.returncode`` -> shell convention: a signal death (-N)
    becomes 128+N, so SIGKILL reads as 137 everywhere instead of -9
    here and 1 there."""
    if code is not None and code < 0:
        return 128 - code
    return code


class ElasticBudgetError(RuntimeError):
    """The gang kept failing until the restart budget ran out. Carries
    the full attempt ``history`` so the operator sees every failure, not
    just the last one."""

    def __init__(self, msg, history=None):
        super().__init__(msg)
        self.history = list(history or [])


def _journal_event(kind, **fields):
    """Supervisor/worker events into the flight recorder when one is
    active (lazy import: elastic must stay importable before obs)."""
    try:
        from ..obs import journal as _journal
    except Exception:
        return
    if _journal.ACTIVE is not None:
        _journal.ACTIVE.event(kind, **fields)


# -- worker side -------------------------------------------------------------


class Heartbeat:
    """Worker-side liveness beacon: an atomically-replaced JSON file
    whose MTIME is the signal (content — ts/pid/step — is diagnostics).
    ``beat()`` belongs in the training loop, once per step: a hang that
    stops the loop must stop the beacon, which is exactly what the
    supervisor's watchdog keys on. With no path configured every call
    is a no-op, so loops can beat unconditionally."""

    def __init__(self, path=None):
        self.path = path
        self.beats = 0

    @classmethod
    def from_env(cls, env=None):
        """The beacon the supervisor configured for this worker (via
        ``PADDLE_TPU_HEARTBEAT_FILE``), or an inert one outside a
        supervised gang."""
        return cls((env or os.environ).get(HEARTBEAT_ENV))

    def beat(self, step=None):
        if self.path is None:
            return
        payload = {"ts": time.time(), "pid": os.getpid()}
        if step is not None:
            payload["step"] = int(step)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, self.path)  # watchdog never reads a torn file
        self.beats += 1


class GracefulShutdown:
    """SIGTERM/SIGINT -> ``.requested``: the preemption notice.

    The handler only sets a flag — the TRAINING LOOP decides when the
    model state is consistent (a step boundary), checkpoints there, and
    calls :meth:`exit_preempted`. Usable as a context manager; install/
    uninstall must run on the main thread (CPython signal rule)."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        self.requested = False
        self.signum = None
        self._prev = {}
        self._installed = False

    def install(self):
        for s in self.signals:
            self._prev[s] = signal.signal(s, self._handler)
        self._installed = True
        return self

    def uninstall(self):
        if self._installed:
            for s, prev in self._prev.items():
                signal.signal(s, prev)
            self._prev.clear()
            self._installed = False

    def _handler(self, signum, frame):
        self.requested = True
        self.signum = signum
        _M_PREEMPT_SIGNALS.inc()
        _journal_event("elastic.preempt_signal", signum=int(signum))

    def exit_preempted(self):
        """Exit with the code the supervisor treats as a preemption
        (restart-eligible, crash-budget-free). Call AFTER the
        checkpoint is durable (``io.wait_checkpoints()``)."""
        sys.exit(PREEMPTED_EXIT_CODE)

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False


def graceful_shutdown(signals=(signal.SIGTERM, signal.SIGINT)):
    """Install and return the worker's preemption handler:
    ``shutdown = resilience.graceful_shutdown()``, then once per step
    boundary ``if shutdown.requested: save_checkpoint(...);
    shutdown.exit_preempted()``."""
    return GracefulShutdown(signals).install()


class ProgramStateAdapter:
    """``state_dict``/``set_state_dict`` protocol over a static
    Program's persistables, so ``save_checkpoint``/``load_checkpoint``
    (manifest, crc, newest-intact fallback, async writer) checkpoint
    the static path exactly like an nn model: pass it as ``model=``."""

    def __init__(self, program, scope=None):
        self.program = program
        self.scope = scope

    def _scope(self):
        from ..static_.program import global_scope

        return self.scope if self.scope is not None else global_scope()

    def state_dict(self):
        from ..framework.io import get_program_persistable_vars

        scope = self._scope()
        out = {}
        for v in get_program_persistable_vars(self.program):
            arr = scope.find_var(v.name)
            if arr is None:  # a silent partial save only fails at resume
                raise ValueError(
                    f"persistable {v.name!r} has no value in scope — run "
                    "the startup program before checkpointing")
            out[v.name] = np.asarray(arr)
        return out

    def set_state_dict(self, state):
        from ..framework.io import set_program_state

        set_program_state(self.program, state)


def fire_step_chaos(step=None, rank=None):
    """Worker-side chaos hook, called once per step boundary: lets the
    ``worker_kill`` / ``worker_hang`` / ``preempt_signal`` injectors
    fire with global-step + rank context. One empty-dict truthiness
    test when chaos is inactive."""
    if not _inject.ACTIVE:
        return
    for point in ("worker_kill", "worker_hang", "preempt_signal"):
        if point in _inject.ACTIVE:
            _inject.fire(point, step=step, rank=rank)


# -- supervisor side ---------------------------------------------------------


def newest_intact_step(directory):
    """Step of the newest checkpoint passing FULL verification, or None
    — what a relaunched worker's ``load_checkpoint`` will resume from.
    The supervisor journals it on every restart, so the flight record
    names each resume point."""
    from ..framework import io as _io

    if not directory or not os.path.isdir(directory):
        return None
    entries = []
    for d in os.listdir(directory):
        if d.startswith("ckpt_"):
            s = _io._ckpt_step(d)
            if s is not None:
                entries.append((s, d))
    for s, d in sorted(entries, reverse=True):
        ok, _ = _io.verify_checkpoint(os.path.join(directory, d))
        if ok:
            return s
    return None


class _Worker:
    __slots__ = ("rank", "proc", "log_fn", "hb_path", "spawned_at",
                 "done", "exit_code")

    def __init__(self, rank, proc, log_fn, hb_path):
        self.rank = rank
        self.proc = proc
        self.log_fn = log_fn
        self.hb_path = hb_path
        self.spawned_at = time.monotonic()
        self.done = False
        self.exit_code = None


class ReplicaSupervisor:
    """The :class:`GangSupervisor` relaunch discipline for INDEPENDENT
    serve replicas (``serving.fleet.ReplicaPool``): per-replica restart
    budget, the same seeded capped-exponential + post-cap-jitter
    backoff schedule (one formula, owned by ``RecoveryPolicy``), and
    ``elastic.replica_restart`` journal events. The crucial difference
    from a training gang: replicas share no collective, so a failed
    replica NEVER tears down its peers — the pool drains/requeues the
    casualty's requests and relaunches it alone while the survivors
    keep serving. Preemption-style exits (``PREEMPTED_EXIT_CODE``) stay
    budget-free, mirroring the gang rules."""

    def __init__(self, max_restarts=3, *, backoff_s=0.5,
                 backoff_factor=2.0, max_backoff_s=30.0, jitter=0.25,
                 seed=0, sleep=None):
        self.max_restarts = int(max_restarts)
        self._policy = _RecoveryPolicy(
            backoff=float(backoff_s), backoff_factor=float(backoff_factor),
            max_backoff=float(max_backoff_s), jitter=float(jitter),
            jitter_seed=int(seed))
        self._sleep = sleep if sleep is not None else time.sleep
        # per-replica budgets: one flapping replica must not spend the
        # healthy ones' relaunches
        self.restarts = {}     # replica_id -> budget-consuming restarts
        self.preemptions = {}  # replica_id -> budget-free relaunches
        self.history = []      # [{replica, kind, code, restarts}]

    def note_failure(self, replica_id, kind="crash", code=None,
                     defer=False):
        """Account one replica failure and SLEEP the backoff before the
        relaunch the caller is about to do. ``kind``: ``crash``/``hang``
        consume that replica's restart budget, ``preempt`` is free.
        Raises :class:`ElasticBudgetError` (with the failure history)
        when the budget is spent. Returns the backoff slept (s).
        ``defer=True`` skips the sleep and just returns the delay — for
        callers that schedule the relaunch themselves instead of
        blocking (the fleet pool's health sweep runs on the router's
        dispatch thread; sleeping there would stall the healthy
        replicas)."""
        rid = int(replica_id)
        free = kind == "preempt"
        if free:
            self.preemptions[rid] = self.preemptions.get(rid, 0) + 1
            n = self.preemptions[rid]
        else:
            self.restarts[rid] = self.restarts.get(rid, 0) + 1
            n = self.restarts[rid]
        self.history.append({"replica": rid, "kind": kind, "code": code,
                             "restarts": self.restarts.get(rid, 0)})
        if not free and n > self.max_restarts:
            _journal_event("elastic.replica_budget_exhausted",
                           replica=rid, restarts=n - 1, last_kind=kind,
                           last_code=code)
            raise ElasticBudgetError(
                f"replica {rid} failed {n} times, restart budget is "
                f"{self.max_restarts}: last failure {kind} "
                f"(exit {code})", self.history)
        delay = 0.0 if free else self._policy.backoff_for(n - 1)
        if not free:
            _M_RESTARTS.inc()
        else:
            _M_PREEMPTIONS.inc()
        _journal_event("elastic.replica_restart", replica=rid,
                       failure=kind, code=code,
                       restarts_used=self.restarts.get(rid, 0),
                       backoff_s=round(delay, 4))
        if delay and not defer:
            self._sleep(delay)
        return delay


class GangSupervisor:
    """Elastic supervisor for one gang of worker processes.

    ``cmd`` is the worker command (list of argv strings) or a callable
    ``(rank, attempt) -> argv``. Each worker inherits the parent env
    plus ``env`` plus ``env_for_rank(rank, attempt)``, a heartbeat path
    in ``PADDLE_TPU_HEARTBEAT_FILE``, and the attempt index in
    ``PADDLE_TPU_ELASTIC_ATTEMPT``. With ``run_dir`` set (default: the
    inherited ``PADDLE_TPU_RUN_DIR``) every worker additionally gets
    ``PADDLE_TPU_RUN_DIR=<run_dir>/rank_NN`` + ``PADDLE_TPU_RANK`` —
    per-rank flight records with one writer per file — and the
    supervisor's own events journal into ``<run_dir>/supervisor``;
    ``obs.fleet`` / ``tools/fleet_report.py`` aggregate the subdirs
    back into one cross-rank view.

    Per attempt, the first of these decides the outcome:

    - every worker exits 0                    -> ``ok`` (done, return 0)
    - a worker exits ``PREEMPTED_EXIT_CODE``  -> ``preempt`` (relaunch,
      budget-free — bounded only by ``max_preempt_restarts``)
    - a worker exits any other nonzero code   -> ``crash``
    - a worker's heartbeat goes stale past ``hang_timeout_s`` (or never
      appears within ``startup_timeout_s``, when that is set; the
      default None keeps non-beating scripts supervisable for plain
      crash/preempt handling) -> SIGKILL it, ``hang``

    On ``crash``/``hang``, one unit of the ``max_restarts`` budget is
    consumed and the relaunch waits a seeded jittered exponential
    backoff; budget exhaustion raises :class:`ElasticBudgetError` with
    the attempt history. Every failure tears down the WHOLE gang
    (SIGTERM, shared grace, SIGKILL — survivors get the chance to
    checkpoint gracefully) before relaunching: workers re-resume from
    the newest intact checkpoint, which keeps the gang's state
    consistent without any cross-worker protocol.
    """

    def __init__(self, cmd, nprocs=1, *, env=None, env_for_rank=None,
                 cwd=None, heartbeat_dir=None, log_dir=None, ckpt_dir=None,
                 run_dir=None, rank_base=0,
                 max_restarts=3, max_preempt_restarts=64,
                 hang_timeout_s=300.0, startup_timeout_s=None,
                 poll_interval_s=0.05, term_grace_s=10.0,
                 backoff_s=0.5, backoff_factor=2.0, max_backoff_s=30.0,
                 jitter=0.25, seed=0, sleep=None):
        self.cmd = cmd
        self.nprocs = int(nprocs)
        self.env = dict(env or {})
        self.env_for_rank = env_for_rank
        self.cwd = cwd
        # fleet observability root: each worker journals into
        # <run_dir>/rank_NN (PADDLE_TPU_RUN_DIR + PADDLE_TPU_RANK per
        # rank — one writer per file, no torn lines by construction)
        # and the supervisor's own events into <run_dir>/supervisor.
        # Defaults to the inherited PADDLE_TPU_RUN_DIR unless the
        # caller's env= explicitly overrides journaling itself.
        if run_dir is None and "PADDLE_TPU_RUN_DIR" not in self.env:
            run_dir = os.environ.get("PADDLE_TPU_RUN_DIR") or None
        self.run_dir = run_dir
        # multi-node gangs: this supervisor owns GLOBAL ranks
        # rank_base..rank_base+nprocs-1 (dist.launch passes
        # node_rank*nproc_per_node), so two nodes sharing one run_dir
        # never journal into the same rank_NN subdir. A nonzero base
        # also suffixes the supervisor's own journal dir — N node
        # supervisors must not co-write one supervisor/journal.jsonl.
        self.rank_base = int(rank_base)
        self._own_hb_dir = heartbeat_dir is None
        self.heartbeat_dir = heartbeat_dir or tempfile.mkdtemp(
            prefix="pt_elastic_hb_")
        self.log_dir = log_dir
        self.ckpt_dir = ckpt_dir
        self.max_restarts = int(max_restarts)
        self.max_preempt_restarts = int(max_preempt_restarts)
        self.hang_timeout_s = float(hang_timeout_s)
        self.startup_timeout_s = (None if startup_timeout_s is None
                                  else float(startup_timeout_s))
        self.poll_interval_s = float(poll_interval_s)
        self.term_grace_s = float(term_grace_s)
        self.backoff_s = float(backoff_s)
        self.backoff_factor = float(backoff_factor)
        self.max_backoff_s = float(max_backoff_s)
        self.jitter = float(jitter)
        self.seed = int(seed)
        # ONE backoff formula in this package: RecoveryPolicy owns the
        # capped-exponential + seeded post-cap jitter schedule
        self._backoff_policy = _RecoveryPolicy(
            backoff=self.backoff_s, backoff_factor=self.backoff_factor,
            max_backoff=self.max_backoff_s, jitter=self.jitter,
            jitter_seed=self.seed)
        self._sleep = sleep if sleep is not None else time.sleep
        self.state = {"attempts": [], "restarts": 0, "preemptions": 0,
                      "watchdog_kills": 0, "exit_code": None}

    # -- spawning / teardown -------------------------------------------------

    def _hb_path(self, rank):
        return os.path.join(self.heartbeat_dir, f"hb_{rank}.json")

    def _spawn(self, attempt):
        os.makedirs(self.heartbeat_dir, exist_ok=True)
        workers = []
        try:
            self._spawn_ranks(workers, attempt)
        except BaseException:
            # a mid-loop Popen/open failure (fork EAGAIN under the very
            # memory pressure that just crashed the gang) must not
            # orphan the ranks already spawned this attempt
            self._teardown(workers)
            raise
        _journal_event("elastic.spawn", attempt=attempt,
                       pids=[w.proc.pid for w in workers])
        return workers

    def _spawn_ranks(self, workers, attempt):
        for rank in range(self.nprocs):
            hb = self._hb_path(rank)
            try:  # a stale beacon from the previous incarnation must
                os.remove(hb)  # not count as liveness (or staleness)
            except OSError:
                pass
            env = dict(os.environ)
            env.update(self.env)
            env[HEARTBEAT_ENV] = hb
            env[ATTEMPT_ENV] = str(attempt)
            if self.run_dir:
                # per-rank flight record under the GLOBAL rank (rank
                # relaunches append into the SAME subdir, so one drill
                # reads as one record); obs.fleet aggregates the
                # subdirs back into one run
                from ..obs.journal import RANK_ENV, rank_subdir

                env["PADDLE_TPU_RUN_DIR"] = os.path.join(
                    self.run_dir, rank_subdir(self.rank_base + rank))
                env[RANK_ENV] = str(self.rank_base + rank)
            env.setdefault("PADDLE_TRAINER_ID",
                           str(self.rank_base + rank))
            env.setdefault("PADDLE_TRAINERS_NUM", str(self.nprocs))
            if self.env_for_rank is not None:
                env.update(self.env_for_rank(rank, attempt) or {})
            argv = self.cmd(rank, attempt) if callable(self.cmd) \
                else list(self.cmd)
            out = None
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                out = open(os.path.join(
                    self.log_dir, f"worker.{rank}.{attempt}.log"), "w")
            try:
                proc = subprocess.Popen(
                    argv, env=env, cwd=self.cwd, stdout=out,
                    stderr=subprocess.STDOUT if out else None)
            except BaseException:
                if out is not None:
                    out.close()
                raise
            workers.append(_Worker(rank, proc, out, hb))

    def _teardown(self, workers):
        """Terminate every survivor: SIGTERM (the graceful-shutdown
        path — survivors may checkpoint), one SHARED grace deadline,
        then SIGKILL; reap all and close logs. No orphaned gang,
        ever."""
        deadline = time.monotonic() + self.term_grace_s
        for w in workers:
            if w.proc.poll() is None:
                try:
                    w.proc.terminate()
                except OSError:
                    pass
        for w in workers:
            try:
                w.proc.wait(timeout=max(
                    0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                w.proc.kill()
                w.proc.wait()
            if w.exit_code is None:
                w.exit_code = normalize_exit_code(w.proc.returncode)
            if w.log_fn is not None:
                w.log_fn.close()

    # -- watchdog ------------------------------------------------------------

    def _check_watchdog(self, workers):
        """Returns the first hung worker, else None. A worker is hung
        when its heartbeat file is stale past ``hang_timeout_s``, or —
        with ``startup_timeout_s`` set — when it never produced one in
        time."""
        now_wall = time.time()
        for w in workers:
            if w.done:
                continue
            try:
                age = now_wall - os.path.getmtime(w.hb_path)
            except OSError:
                if self.startup_timeout_s is not None and \
                        time.monotonic() - w.spawned_at > \
                        self.startup_timeout_s:
                    return w, None
                continue
            if age > self.hang_timeout_s:
                return w, age
        return None

    # -- the supervise loop --------------------------------------------------

    def _supervise(self, workers, resume_t0=None):
        """Wait for the gang; returns the attempt outcome dict. Each
        poll: reap exits (0 -> done; PREEMPTED -> preempt; other ->
        crash), then the heartbeat watchdog (-> SIGKILL + hang), then —
        once after a relaunch — the resume-latency sample (failure
        detection to every worker beating again)."""
        resume_pending = resume_t0 is not None
        while True:
            for w in workers:
                if w.done:
                    continue
                rc = w.proc.poll()
                if rc is None:
                    continue
                w.done = True
                w.exit_code = normalize_exit_code(rc)
                if w.exit_code == 0:
                    continue
                kind = ("preempt" if w.exit_code == PREEMPTED_EXIT_CODE
                        else "crash")
                return {"kind": kind, "rank": w.rank,
                        "code": w.exit_code,
                        "detected_at": time.monotonic()}
            if all(w.done for w in workers):
                return {"kind": "ok", "detected_at": time.monotonic()}
            hung = self._check_watchdog(workers)
            if hung is not None:
                w, stale_s = hung
                try:
                    w.proc.kill()  # SIGTERM can't help a wedged loop
                except OSError:
                    pass
                w.done = True
                w.exit_code = normalize_exit_code(w.proc.wait())
                _M_WATCHDOG.inc()
                self.state["watchdog_kills"] += 1
                _journal_event(
                    "elastic.watchdog_kill", rank=w.rank,
                    stale_s=(None if stale_s is None
                             else round(stale_s, 3)),
                    startup=stale_s is None)
                return {"kind": "hang", "rank": w.rank,
                        "code": w.exit_code,
                        "detected_at": time.monotonic()}
            if resume_pending and all(
                    os.path.exists(w.hb_path) for w in workers):
                # beacons were cleared at spawn: existence == the new
                # incarnation made its first step. That closes the
                # failure->productive-again window MFU/goodput loses.
                ms = (time.monotonic() - resume_t0) * 1e3
                _M_RESUME_MS.observe(ms)
                _journal_event("elastic.resumed", resume_ms=ms)
                resume_pending = False
            self._sleep(self.poll_interval_s)

    def _backoff(self, n):
        """Backoff before restart ``n`` (0-based): exponential, capped,
        then spread ±``jitter`` via ``RandomState(seed + n)`` — many
        supervisors recovering from one outage must not relaunch in
        lockstep, and the same seed must replay the same drill. Delegates
        to :meth:`RecoveryPolicy.backoff_for` (the one formula)."""
        return self._backoff_policy.backoff_for(n)

    def _open_supervisor_journal(self):
        """With ``run_dir`` set, the supervisor's OWN events
        (elastic.start/restart/watchdog_kill/...) get their own journal
        at ``<run_dir>/supervisor`` — never a worker's file, so the
        flight record is multi-process without a single multi-writer
        line. Installed for the supervise loop and restored after;
        returns ``(journal, previous_active)`` (``(None, None)`` when
        run_dir is unset, journaling failed, or the caller already
        journals there)."""
        if not self.run_dir:
            return None, None
        try:
            from ..obs import journal as _journal
        except Exception:
            return None, None
        sup_name = _journal.SUPERVISOR_DIR if not self.rank_base \
            else f"{_journal.SUPERVISOR_DIR}_{self.rank_base:02d}"
        sup_dir = os.path.join(self.run_dir, sup_name)
        prev = _journal.ACTIVE
        if prev is not None and os.path.abspath(prev.run_dir) == \
                os.path.abspath(sup_dir):
            return None, None
        # the supervisor is rank-less even when IT runs inside a ranked
        # worker (nested gangs): mask the inherited rank for the
        # construct-or the journal would nest a rank subdir under
        # supervisor/
        saved_rank = os.environ.pop(_journal.RANK_ENV, None)
        try:
            j = _journal.RunJournal(sup_dir)
            j.start()
        except Exception:
            return None, None
        finally:
            if saved_rank is not None:
                os.environ[_journal.RANK_ENV] = saved_rank
        _journal.ACTIVE = j
        return j, prev

    def run(self):
        """Supervise until the gang completes (returns 0), or the
        restart budget is exhausted (raises
        :class:`ElasticBudgetError`)."""
        attempt = 0
        restarts_used = 0
        preempts_used = 0
        resume_t0 = None
        sup_journal, prev_journal = self._open_supervisor_journal()
        _journal_event("elastic.start", nprocs=self.nprocs,
                       max_restarts=self.max_restarts,
                       hang_timeout_s=self.hang_timeout_s)
        try:
            while True:
                workers = self._spawn(attempt)
                try:
                    outcome = self._supervise(workers,
                                              resume_t0=resume_t0)
                finally:
                    self._teardown(workers)
                self.state["attempts"].append(
                    {k: v for k, v in outcome.items()
                     if k != "detected_at"})
                if outcome["kind"] == "ok":
                    self.state["exit_code"] = 0
                    _journal_event("elastic.done", attempts=attempt + 1,
                                   restarts=restarts_used,
                                   preemptions=preempts_used)
                    return 0
                resume_t0 = outcome["detected_at"]
                resume_step = newest_intact_step(self.ckpt_dir)
                if outcome["kind"] == "preempt":
                    preempts_used += 1
                    _M_PREEMPTIONS.inc()
                    self.state["preemptions"] += 1
                    _journal_event("elastic.preempt",
                                   rank=outcome["rank"], attempt=attempt,
                                   resume_step=resume_step)
                    if preempts_used > self.max_preempt_restarts:
                        raise ElasticBudgetError(
                            f"gang preempted {preempts_used} times "
                            f"(max_preempt_restarts="
                            f"{self.max_preempt_restarts})",
                            self.state["attempts"])
                else:  # crash / hang: consumes the restart budget
                    restarts_used += 1
                    if restarts_used > self.max_restarts:
                        self.state["exit_code"] = outcome.get("code")
                        _journal_event(
                            "elastic.budget_exhausted",
                            restarts=restarts_used - 1,
                            last_kind=outcome["kind"],
                            last_rank=outcome["rank"],
                            last_code=outcome.get("code"))
                        raise ElasticBudgetError(
                            f"gang failed {restarts_used} times, restart "
                            f"budget is {self.max_restarts}: last "
                            f"failure rank {outcome['rank']} "
                            f"{outcome['kind']} "
                            f"(exit {outcome.get('code')})",
                            self.state["attempts"])
                    _M_RESTARTS.inc()
                    self.state["restarts"] += 1
                    delay = self._backoff(restarts_used - 1)
                    _journal_event(
                        "elastic.restart", failure=outcome["kind"],
                        rank=outcome["rank"], code=outcome.get("code"),
                        attempt=attempt, restarts_used=restarts_used,
                        backoff_s=round(delay, 4),
                        resume_step=resume_step)
                    self._sleep(delay)
                attempt += 1
        finally:
            if sup_journal is not None:
                from ..obs import journal as _journal

                try:
                    sup_journal.close()
                except Exception:
                    pass
                # close() clears ACTIVE when it still points here;
                # restore whatever journal the caller had installed
                if _journal.ACTIVE is None and prev_journal is not None \
                        and not prev_journal.closed:
                    _journal.ACTIVE = prev_journal
            if self._own_hb_dir:
                import shutil

                shutil.rmtree(self.heartbeat_dir, ignore_errors=True)
