"""paddle_tpu.resilience — fault injection & recovery.

The policy layer that turns the stack's existing fault *primitives*
(``utils/nan_guard.py`` detection, atomic ``framework/io.py``
checkpoints, the threaded ``io_/dataloader.py``) into recovered runs,
plus the chaos machinery that proves it: every registered injector in
``inject.INJECTORS`` has a recovery test (``tests/test_resilience.py``)
and a CLI scenario (``tools/chaos_run.py --self-test``).

Reference analogs: ``FLAGS_check_nan_inf`` (operator.cc per-op abort),
``fluid/incubate/checkpoint`` + fleet HA utilities (checkpoint hygiene,
trainer restart). See SURVEY §2 rows 45/61.
"""
from . import inject  # noqa: F401
from .inject import (  # noqa: F401
    ACTIVE, INJECTORS, ChaosError, SimulatedCrashError, TransientChaosError,
    WorkerCrashChaos, chaos, install_from_env,
)
from .policy import (  # noqa: F401
    NONFINITE_ACTIONS, RecoveryPolicy, TransientError, retry_call,
)
from .guard import GuardedExecutor, GuardedStep, GuardStats  # noqa: F401
from .elastic import (  # noqa: F401
    PREEMPTED_EXIT_CODE, ElasticBudgetError, GangSupervisor,
    GracefulShutdown, Heartbeat, ProgramStateAdapter, ReplicaSupervisor,
    fire_step_chaos, graceful_shutdown, newest_intact_step,
    normalize_exit_code,
)

__all__ = [
    "chaos", "install_from_env", "ACTIVE", "INJECTORS",
    "ChaosError", "TransientChaosError", "WorkerCrashChaos",
    "SimulatedCrashError", "TransientError",
    "RecoveryPolicy", "NONFINITE_ACTIONS", "retry_call",
    "GuardedStep", "GuardedExecutor", "GuardStats",
    "PREEMPTED_EXIT_CODE", "ElasticBudgetError", "GangSupervisor",
    "GracefulShutdown", "Heartbeat", "ProgramStateAdapter",
    "ReplicaSupervisor",
    "fire_step_chaos", "graceful_shutdown", "newest_intact_step",
    "normalize_exit_code",
]
